"""Fleet observability plane: cross-worker aggregation + straggler
attribution (ISSUE 18).

The elastic fleet runtime (tpu_mx/parallel/fleet.py, PR 15) made
membership dynamic, but every observability layer stayed per-process:
the controller evicted and resharded workers without ever seeing which
rank was slow, why a collective stalled, or the fleet-wide step rate.
This module closes that gap in three layers:

- **Shipping** (worker side, :class:`ObsShipper`): each worker exports a
  rolling whole-file snapshot of its telemetry registry to
  ``<fleet_dir>/obs/rank-N.jsonl`` and its recent flight-recorder events
  (plus trace context and ring stats) to
  ``<fleet_dir>/obs/rank-N-events.json``, both through
  ``checkpoint.atomic_write`` so the controller can never read a torn
  file.  Rate-limited like the capacity forensics dumps (one export per
  ``interval`` seconds, forced on :meth:`~tpu_mx.parallel.fleet.Fleet.leave`);
  degrades silently when no fleet is armed.  Every shipped record and
  event carries the fleet identity stamp (``rank`` +
  ``fleet_generation``, tpu_mx/telemetry.py ``set_fleet_identity`` /
  tpu_mx/tracing.py context) the merge keys stale exclusion on.

- **Merging** (:func:`merge_streams`, pure — loadable standalone by
  tools/fleet_report.py and tools/telemetry_report.py ``--merge``):
  counters SUM across ranks, histograms bucket-merge (the fixed-ladder
  edges make cumulative counts element-wise summable by construction;
  mismatched edges refuse loudly), gauges keep per-rank values plus
  min/max/mean.  The exactness invariant — the fleet counter equals the
  sum of the per-rank counters it merged, re-checkable from the
  ``per_rank`` breakdown every merged record carries — is asserted by
  tests, by ``fleet_report --validate`` and by the soak CI leg.
  Records stamped with a membership generation other than the
  aggregation's are EXCLUDED (an evicted rank's stale snapshot must not
  pollute the new epoch's rollup); a rank with no readable snapshot is
  a reported gap (``fleet.ranks_reporting``), never interpolated.

- **Attribution** (:func:`correlate_steps` + :class:`StragglerDetector`):
  per-rank ``train_step.phase`` events are correlated by
  ``(epoch, step, fleet_generation)`` across ranks into per-step skew
  (``fleet.step_skew_seconds``) and a slowest-rank attribution whose
  dominant phase is the one that explains the gap to the fastest rank.
  A windowed detector (a rank slowest in >= ``frac`` of the last
  ``window`` correlated steps) feeds the ``fleet.straggler_signal``
  hook — the ``scheduler.slo_signal``/``capacity_signal`` twin — that
  ``tools/launch.py --supervise`` surfaces in evict/degrade decisions
  and in the fleet black box.

The controller-side :class:`FleetAggregator` runs the whole pass per
poll and publishes the cataloged ``fleet.*`` rollup metrics;
:func:`dump_fleet_blackbox` extends the PR 15 black box with a
cross-rank section (per-rank events + telemetry aligned on membership
generation, the skew timeline, the straggler signal and the merged
aggregate) rendered jax-lessly by ``tools/fleet_report.py``.

Like telemetry.py and tracing.py, the merge/attribution core imports
ONLY the stdlib: the module is loadable standalone from its file (the
package bridges degrade to None), so the report tools never boot jax
just to re-check an identity.
"""
from __future__ import annotations

import json
import os
import re
import time
from collections import deque

try:
    from .. import checkpoint as _ckpt
    from .. import telemetry as _telemetry
    from .. import tracing as _tracing
except ImportError:  # standalone module load (tools/fleet_report.py)
    _ckpt = _telemetry = _tracing = None

__all__ = ["OBS_DIR", "OBS_FORMAT", "FLEET_SECTION_FORMAT", "ObsShipper",
           "FleetAggregator", "StragglerDetector", "merge_streams",
           "correlate_steps", "read_obs_dir", "read_integrity_dir",
           "fleet_blackbox_path", "dump_fleet_blackbox",
           "validate_fleet_section"]

#: subdirectory of the fleet membership store holding shipped snapshots
OBS_DIR = "obs"
#: format tag of the per-rank events document
OBS_FORMAT = "tpu_mx-fleet-obs-v1"
#: format tag of the fleet section a fleet black box carries
FLEET_SECTION_FORMAT = "tpu_mx-fleet-section-v1"

#: the phases cross-rank attribution correlates (the host-side stations
#: of the compiled train step, tracing.TRAIN_STEP_PHASES)
ATTRIBUTION_PHASES = ("data_wait", "recompile", "dispatch",
                      "loss_readback", "optimizer_update")

_RANK_JSONL = re.compile(r"^rank-(\d+)\.jsonl$")
_RANK_EVENTS = re.compile(r"^rank-(\d+)-events\.json$")

#: the SDC defense plane's on-disk state (tpu_mx/parallel/integrity.py
#: and Fleet.quarantine write these; read here stdlib-only so the
#: forensics tools never boot jax to render a corruption verdict)
INTEGRITY_DIR = "integrity"
QUARANTINE_DIR = "quarantine"
_RANK_FP = re.compile(r"^fp-(\d+)\.json$")
_RANK_VOTES = re.compile(r"^votes-(\d+)\.jsonl$")
_RANK_QUARANTINE = re.compile(r"^(\d+)\.json$")


# ---------------------------------------------------------------------------
# worker side: shipping
# ---------------------------------------------------------------------------
class ObsShipper:
    """Rate-limited exporter of ONE worker's observability state into the
    fleet store.  Constructed lazily by ``Fleet.on_step`` (worker side
    only); every public entry point degrades to a no-op when the handle
    has no member slot or the package bridges are absent."""

    def __init__(self, fleet, interval=1.0, last_events=200):
        self.fleet = fleet
        self.interval = float(interval)
        self.last_events = int(last_events)
        self._next = 0.0          # monotonic deadline for the next export
        self.ships = 0

    def paths(self):
        """(snapshot_jsonl, events_json) for this worker's rank."""
        rank = int(self.fleet.member)
        obs = os.path.join(self.fleet.root, OBS_DIR)
        return (os.path.join(obs, f"rank-{rank}.jsonl"),
                os.path.join(obs, f"rank-{rank}-events.json"))

    def ship(self, force=False):
        """Export this rank's telemetry snapshot + recent events (whole-
        file atomic rewrites — the controller reads complete snapshots
        or nothing).  Returns the snapshot path, or None when rate-
        limited / not a fleet worker."""
        if (self.fleet.member is None or _telemetry is None
                or _ckpt is None):
            return None
        now = time.monotonic()
        if not force and now < self._next:
            return None
        self._next = now + self.interval
        rank = int(self.fleet.member)
        jsonl, events_path = self.paths()
        os.makedirs(os.path.dirname(jsonl), exist_ok=True)
        _telemetry._refresh_bridge_gauges()
        recs = _telemetry.snapshot()
        payload = "".join(json.dumps(r, sort_keys=True) + "\n"
                          for r in recs)
        with _ckpt.atomic_write(jsonl, mode="w") as f:
            f.write(payload)
        doc = {
            "format": OBS_FORMAT,
            "rank": rank,
            "generation": self.fleet.acked_generation,
            "wall_time": time.time(),
            "context": _tracing.get_context(),
            "stats": _tracing.stats(),
            "events": _tracing.snapshot(last=self.last_events),
        }
        body = _strict_json(doc)
        with _ckpt.atomic_write(events_path, mode="w") as f:
            f.write(body)
        self.ships += 1
        # counted AFTER the export: shipped snapshot N carries the count
        # through export N-1 — the off-by-one is inherent to counting
        # one's own shipping and harmless to the sum identity
        _telemetry.counter("fleet.obs_records").inc(len(recs))
        return jsonl


def _strict_json(doc):
    """Strict-JSON serialization with the same non-finite fallback as
    ``tracing.dump_blackbox``: events are non-finite-safe by
    construction, but a gauge someone set to NaN must not lose the
    export."""
    try:
        return json.dumps(doc, sort_keys=True, allow_nan=False)
    except ValueError:
        return json.dumps(doc, sort_keys=True)


# ---------------------------------------------------------------------------
# merge core (pure; shared with tools/telemetry_report.py --merge)
# ---------------------------------------------------------------------------
def _labels_json(rec):
    return json.dumps(rec.get("labels", {}), sort_keys=True)


def _last_per_series(records):
    """{(name, labels_json): record} — the LAST record per series wins
    (shipped snapshots are cumulative, exactly like a JSONL flush)."""
    out = {}
    for rec in records:
        name = rec.get("name")
        if isinstance(name, str) and name:
            out[(name, _labels_json(rec))] = rec
    return out


def _bucket_bounds(buckets):
    return [b for b, _ in buckets]


def _sum_buckets(name, acc, add):
    """Element-wise sum of two record-shaped cumulative bucket lists —
    valid because cumulation is linear.  Refuses loudly on mismatched
    edges: the fixed bucket ladders make edges identical across ranks
    by construction, so a mismatch is corruption, not a case to paper
    over."""
    if _bucket_bounds(acc) != _bucket_bounds(add):
        raise ValueError(
            f"{name}: histogram bucket edges differ across ranks — "
            "refusing to merge (fixed ladders should make them "
            "identical; this snapshot is corrupt or from another build)")
    return [[b, c + c2] for (b, c), (_, c2) in zip(acc, add)]


def _merge_window(kind, wins):
    """Merge the ``window`` sub-objects that exist (None entries are
    ranks whose record predates the window layer).  ``seconds`` is the
    widest coverage (windows are wall-clock-aligned per rank, so the
    union is bounded by the max), values/counts sum."""
    wins = [w for w in wins if isinstance(w, dict)]
    if not wins:
        return None
    out = {"seconds": max(float(w.get("seconds", 0.0)) for w in wins)}
    if kind == "counter":
        out["value"] = sum(w.get("value", 0) for w in wins)
        return out
    out["count"] = sum(int(w.get("count", 0)) for w in wins)
    out["sum"] = sum(float(w.get("sum", 0.0)) for w in wins)
    mins = [w["min"] for w in wins if isinstance(w.get("min"), (int, float))]
    maxs = [w["max"] for w in wins if isinstance(w.get("max"), (int, float))]
    if mins:
        out["min"], out["max"] = min(mins), max(maxs)
    buckets = None
    for w in wins:
        wb = w.get("buckets")
        if not isinstance(wb, list) or not wb:
            continue
        buckets = wb if buckets is None \
            else _sum_buckets("window", buckets, wb)
    if buckets is not None:
        out["buckets"] = buckets
    return out


def merge_streams(streams, generation=None):
    """Merge per-rank record streams into fleet rollup records.

    ``streams`` is ``{rank: [record, ...]}`` (each rank's LAST record
    per (name, labels) series wins).  When ``generation`` is given,
    records stamped with a DIFFERENT ``fleet_generation`` are excluded
    as stale (the evicted-rank rule); unstamped records are kept — a
    controller's own registry legitimately lacks the stamp.

    Returns ``(merged, info)``: ``merged`` is a list of record-shaped
    dicts — counters summed, histograms bucket-merged, gauges carrying
    ``min``/``max``/``mean`` — each with a ``per_rank`` value breakdown
    and the sorted contributing ``ranks`` (the re-checkable exactness
    invariant: ``value == sum(per_rank.values())`` for counters).
    ``info`` is ``{"ranks", "stale_dropped", "records_read"}`` — ranks
    that contributed nothing (missing or fully stale) are simply absent
    from ``info["ranks"]``, never interpolated.
    """
    per_rank_series = {}
    stale = 0
    read = 0
    for rank, records in streams.items():
        rank = int(rank)
        kept = []
        for rec in records:
            read += 1
            gen = rec.get("fleet_generation")
            if (generation is not None and gen is not None
                    and int(gen) != int(generation)):
                stale += 1
                continue
            kept.append(rec)
        last = _last_per_series(kept)
        if last:
            per_rank_series[rank] = last
    # series key -> {rank: record}
    by_series = {}
    for rank, last in sorted(per_rank_series.items()):
        for key, rec in last.items():
            by_series.setdefault(key, {})[rank] = rec
    merged = []
    for (name, lj), by_rank in sorted(by_series.items()):
        ranks = sorted(by_rank)
        recs = [by_rank[r] for r in ranks]
        kind = recs[0].get("type")
        out = {"name": name, "type": kind,
               "ts": max(float(r.get("ts", 0.0)) for r in recs),
               "ranks": ranks,
               "per_rank": {str(r): by_rank[r].get("value")
                            for r in ranks}}
        labels = json.loads(lj)
        if labels:
            out["labels"] = labels
        if generation is not None:
            out["fleet_generation"] = int(generation)
        if kind == "counter":
            out["value"] = sum(r.get("value", 0) for r in recs)
            win = _merge_window("counter", [r.get("window") for r in recs])
            if win is not None:
                out["window"] = win
        elif kind == "histogram":
            out["value"] = sum(int(r.get("value", 0)) for r in recs)
            out["sum"] = sum(float(r.get("sum", 0.0)) for r in recs)
            units = {r.get("unit", "seconds") for r in recs}
            out["unit"] = units.pop() if len(units) == 1 else "seconds"
            mins = [r["min"] for r in recs
                    if isinstance(r.get("min"), (int, float))]
            maxs = [r["max"] for r in recs
                    if isinstance(r.get("max"), (int, float))]
            if mins:
                out["min"], out["max"] = min(mins), max(maxs)
            dropped = sum(int(r.get("dropped_nonfinite", 0)) for r in recs)
            if dropped:
                out["dropped_nonfinite"] = dropped
            buckets = None
            for r in recs:
                rb = r.get("buckets")
                if not isinstance(rb, list) or not rb:
                    continue
                buckets = rb if buckets is None \
                    else _sum_buckets(name, buckets, rb)
            if buckets is not None:
                out["buckets"] = buckets
            win = _merge_window("histogram",
                                [r.get("window") for r in recs])
            if win is not None:
                out["window"] = win
        else:  # gauge: per-rank values + min/max/mean — never summed
            vals = [float(r.get("value", 0.0)) for r in recs]
            out["value"] = sum(vals) / len(vals)
            out["min"] = min(vals)
            out["max"] = max(vals)
            out["mean"] = out["value"]
        merged.append(out)
    info = {"ranks": sorted(per_rank_series),
            "stale_dropped": stale,
            "records_read": read}
    return merged, info


# ---------------------------------------------------------------------------
# cross-rank step correlation + the persistent-straggler detector
# ---------------------------------------------------------------------------
def correlate_steps(events_by_rank, generation=None):
    """Correlate per-rank ``train_step.phase`` events by
    ``(epoch, step, fleet_generation)`` into per-step skew records.

    ``events_by_rank`` is ``{rank: [event, ...]}`` (shipped flight-
    recorder snapshots).  Only steps observed by >= 2 ranks correlate —
    a single-rank step has no skew.  When ``generation`` is given, only
    steps of that membership generation are kept (the cross-rank
    timeline is aligned on the membership epoch: the same (epoch, step)
    pair under different world shapes is a different step).

    Returns a list sorted by (generation, epoch, step); each entry::

        {"generation", "epoch", "step",
         "ranks": {str(rank): {"total": sec, "phases": {phase: sec}}},
         "skew_seconds": max-min of per-rank totals,
         "slowest_rank", "fastest_rank",
         "dominant_phase": the phase explaining the largest share of
                           the slowest-vs-fastest gap}
    """
    per_key = {}
    for rank, events in events_by_rank.items():
        rank = int(rank)
        for ev in events:
            if ev.get("event") != "train_step.phase":
                continue
            epoch, step = ev.get("epoch"), ev.get("step")
            if not isinstance(epoch, int) or not isinstance(step, int):
                continue
            gen = ev.get("fleet_generation")
            gen = 0 if not isinstance(gen, int) else gen
            if generation is not None and gen != int(generation):
                continue
            data = ev.get("data", {})
            phase = data.get("phase")
            secs = data.get("seconds")
            if phase not in ATTRIBUTION_PHASES \
                    or not isinstance(secs, (int, float)):
                continue  # non-finite seconds ship as strings: skip
            slot = per_key.setdefault((gen, epoch, step), {}) \
                          .setdefault(rank, {})
            slot[phase] = slot.get(phase, 0.0) + float(secs)
    out = []
    for (gen, epoch, step), by_rank in sorted(per_key.items()):
        if len(by_rank) < 2:
            continue
        totals = {r: sum(p.values()) for r, p in by_rank.items()}
        slowest = max(totals, key=lambda r: (totals[r], r))
        fastest = min(totals, key=lambda r: (totals[r], -r))
        slow_p, fast_p = by_rank[slowest], by_rank[fastest]
        # the dominant phase is the one explaining the largest share of
        # the slowest-vs-fastest GAP — not the slowest rank's absolute
        # argmax, which a fat dispatch phase every rank pays would win
        gaps = {ph: slow_p.get(ph, 0.0) - fast_p.get(ph, 0.0)
                for ph in set(slow_p) | set(fast_p)}
        dominant = max(gaps, key=lambda ph: (gaps[ph], ph))
        out.append({
            "generation": gen, "epoch": epoch, "step": step,
            "ranks": {str(r): {"total": totals[r],
                               "phases": dict(by_rank[r])}
                      for r in sorted(by_rank)},
            "skew_seconds": totals[slowest] - totals[fastest],
            "slowest_rank": slowest,
            "fastest_rank": fastest,
            "dominant_phase": dominant,
        })
    return out


class StragglerDetector:
    """Windowed persistent-straggler detection over correlated steps.

    One slow step is noise; the detector fires only when the SAME rank
    is the slowest in >= ``frac`` of the last ``window`` correlated
    steps (and at least ``min_steps`` have been judged).  ``signal`` is
    the published hook dict — the ``scheduler.slo_signal`` twin the
    fleet supervisor consumes::

        {"straggling": bool, "rank": int (-1 = none),
         "excess_seconds": mean skew of the rank's slowest steps,
         "dominant_phase": modal dominant phase, "steps": judged count,
         "window": window}

    State flips land on the flight-recorder timeline as
    ``fleet.straggler`` events.
    """

    def __init__(self, window=12, frac=0.5, min_steps=4,
                 min_excess_seconds=0.0):
        self.window = int(window)
        self.frac = float(frac)
        self.min_steps = int(min_steps)
        self.min_excess_seconds = float(min_excess_seconds)
        self._history = deque(maxlen=self.window)
        self._latest = None       # highest (gen, epoch, step) judged
        self.signal = self._clear()

    def _clear(self):
        return {"straggling": False, "rank": -1, "excess_seconds": 0.0,
                "dominant_phase": "", "steps": 0, "window": self.window}

    def update(self, correlated):
        """Feed a (re-read, possibly overlapping) correlated-step list;
        only steps NEWER than the last judged one enter the window —
        shipped event snapshots are rolling, so every poll re-reads the
        recent past.  Returns the (possibly flipped) signal dict."""
        for c in correlated:
            key = (c["generation"], c["epoch"], c["step"])
            if self._latest is not None and key <= self._latest:
                continue
            self._latest = key
            self._history.append((c["slowest_rank"], c["skew_seconds"],
                                  c["dominant_phase"]))
        return self._evaluate()

    def _evaluate(self):
        prev = dict(self.signal)
        n = len(self._history)
        new = self._clear()
        if n >= self.min_steps:
            counts = {}
            for rank, _skew, _ph in self._history:
                counts[rank] = counts.get(rank, 0) + 1
            rank = max(counts, key=lambda r: (counts[r], r))
            entries = [(s, ph) for r, s, ph in self._history if r == rank]
            excess = sum(s for s, _ in entries) / len(entries)
            if (counts[rank] >= self.frac * n
                    and excess >= self.min_excess_seconds):
                phases = {}
                for _, ph in entries:
                    phases[ph] = phases.get(ph, 0) + 1
                new = {"straggling": True, "rank": int(rank),
                       "excess_seconds": excess,
                       "dominant_phase": max(phases,
                                             key=lambda p: (phases[p], p)),
                       "steps": len(entries), "window": self.window}
        self.signal = new
        if (new["straggling"], new["rank"]) != (prev["straggling"],
                                                prev["rank"]) \
                and _tracing is not None:
            _tracing.emit("fleet.straggler", rank=new["rank"],
                          excess_seconds=float(new["excess_seconds"]),
                          phase=new["dominant_phase"],
                          steps=int(new["steps"]))
        return dict(new)


# ---------------------------------------------------------------------------
# controller side: the aggregation pass
# ---------------------------------------------------------------------------
def read_obs_dir(root):
    """Read every shipped snapshot under ``<root>/obs/``.

    Returns ``({rank: [record, ...]}, {rank: events_doc})``.  Unreadable
    or half-written files are skipped (atomic_write makes that rare;
    a skipped rank is a reported gap, not an error)."""
    obs = os.path.join(root, OBS_DIR)
    streams, docs = {}, {}
    try:
        names = sorted(os.listdir(obs))
    except OSError:
        return streams, docs
    for name in names:
        path = os.path.join(obs, name)
        m = _RANK_JSONL.match(name)
        if m:
            recs = []
            try:
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if isinstance(rec, dict):
                            recs.append(rec)
            except OSError:
                continue
            if recs:
                streams[int(m.group(1))] = recs
            continue
        m = _RANK_EVENTS.match(name)
        if m:
            try:
                with open(path, encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            if isinstance(doc, dict) and doc.get("format") == OBS_FORMAT:
                docs[int(m.group(1))] = doc
    return streams, docs


def read_integrity_dir(fleet_dir, last_votes=50):
    """Read the SDC defense plane's on-disk state under ``fleet_dir``.

    Returns the black box's ``corruption`` section: each rank's newest
    published fingerprint (``integrity/fp-<rank>.json``), the tail of
    each rank's vote journal (``integrity/votes-<rank>.jsonl``), every
    permanent quarantine record (``quarantine/<rank>.json``), and a
    one-object ``verdict`` summarising them — ``clean`` is True only
    when no vote ever disagreed AND no rank is quarantined.  Unreadable
    or half-written files are skipped, same policy as
    :func:`read_obs_dir`: a gap is reported, never raised."""
    root = os.fspath(fleet_dir)
    fingerprints, votes_by_rank, quarantined = {}, {}, {}
    idir = os.path.join(root, INTEGRITY_DIR)
    try:
        names = sorted(os.listdir(idir))
    except OSError:
        names = []
    for name in names:
        path = os.path.join(idir, name)
        m = _RANK_FP.match(name)
        if m:
            try:
                with open(path, encoding="utf-8") as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            if isinstance(rec, dict):
                fingerprints[str(int(m.group(1)))] = rec
            continue
        m = _RANK_VOTES.match(name)
        if m:
            recs = []
            try:
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if isinstance(rec, dict):
                            recs.append(rec)
            except OSError:
                continue
            if recs:
                votes_by_rank[str(int(m.group(1)))] = recs[-last_votes:]
    qdir = os.path.join(root, QUARANTINE_DIR)
    try:
        qnames = sorted(os.listdir(qdir))
    except OSError:
        qnames = []
    for name in qnames:
        m = _RANK_QUARANTINE.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(qdir, name), encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict):
            quarantined[str(int(m.group(1)))] = rec
    mismatch_steps, suspected = set(), set()
    for recs in votes_by_rank.values():
        for v in recs:
            if not v.get("agree", True):
                mismatch_steps.add(int(v.get("step", -1)))
                suspected.update(int(m) for m in v.get("minority", []))
    return {
        "fingerprints": fingerprints,
        "votes_by_rank": votes_by_rank,
        "quarantined": quarantined,
        "verdict": {
            "clean": not mismatch_steps and not quarantined,
            "mismatch_steps": sorted(mismatch_steps),
            "suspected": sorted(suspected),
            "quarantined": sorted(int(r) for r in quarantined),
        },
    }


class FleetAggregator:
    """The controller's periodic merge pass over ``<fleet_dir>/obs/``.

    ``poll()`` (rate-limited; ``force=True`` for dump paths) reads every
    rank's shipped snapshot, merges at the CURRENT membership
    generation, correlates phases, updates the straggler detector, and
    publishes the ``fleet.*`` rollup metrics into the controller's own
    registry.  Rollups are published under NEW names only — per-rank
    worker metrics are returned, never re-registered under their own
    names in the controller (the controller may itself train; replaying
    worker counters into its registry would double-count)."""

    def __init__(self, fleet, interval=1.0, detector=None):
        self.fleet = fleet
        self.interval = float(interval)
        self.detector = detector or StragglerDetector()
        self._next = 0.0
        self.last = None

    def poll(self, force=False):
        """Run one aggregation pass (or return the cached one inside the
        rate-limit window).  Returns the pass result dict, or None when
        nothing has been shipped yet."""
        now = time.monotonic()
        if not force and now < self._next:
            return self.last
        self._next = now + self.interval
        streams, docs = read_obs_dir(self.fleet.root)
        generation = self.fleet.generation
        merged, info = merge_streams(streams, generation=generation)
        events_by_rank = {r: d.get("events", []) for r, d in docs.items()
                          if isinstance(d.get("events"), list)}
        # no generation FILTER here: the correlation key already carries
        # the membership generation (same (epoch, step) under another
        # epoch is a different step), and the post-mortem skew timeline
        # must keep the steps that led UP to a churn — only the metric
        # MERGE excludes stale-generation records
        correlated = correlate_steps(events_by_rank)
        signal = self.detector.update(correlated)
        self.last = {
            "generation": generation,
            "world": self.fleet.world(),
            "merged": merged,
            "info": info,
            "streams": streams,
            "docs": docs,
            "correlated": correlated,
            "signal": signal,
            "wall_time": time.time(),
        }
        self._publish(self.last)
        return self.last

    def _publish(self, res):
        if _telemetry is None:
            return
        info = res["info"]
        _telemetry.gauge("fleet.ranks_reporting").set(len(info["ranks"]))
        stamps = [d.get("wall_time") for d in res["docs"].values()
                  if isinstance(d.get("wall_time"), (int, float))]
        if stamps:
            _telemetry.gauge("fleet.agg_lag_seconds").set(
                max(0.0, res["wall_time"] - min(stamps)))
        for rec in res["merged"]:
            if rec["name"] == "train_step.steps" and not rec.get("labels"):
                win = rec.get("window") or {}
                secs = float(win.get("seconds", 0.0))
                if secs > 0:
                    _telemetry.gauge("fleet.step_rate").set(
                        float(win.get("value", 0)) / secs)
        if res["correlated"]:
            _telemetry.gauge("fleet.step_skew_seconds").set(
                res["correlated"][-1]["skew_seconds"])
        sig = res["signal"]
        _telemetry.gauge("fleet.straggler_signal").set(
            1.0 if sig["straggling"] else 0.0)
        _telemetry.gauge("fleet.straggler_rank").set(float(sig["rank"]))


# ---------------------------------------------------------------------------
# the fleet black box
# ---------------------------------------------------------------------------
def fleet_blackbox_path(fleet_dir):
    return os.path.join(os.fspath(fleet_dir), "fleet-blackbox.json")


def _fleet_section(res):
    """The cross-rank section a fleet black box carries, built from one
    aggregation pass so the per-rank data and the aggregate are a
    consistent read (the identity re-check depends on that)."""
    ranks = {}
    for r in sorted(set(res["streams"]) | set(res["docs"])):
        doc = res["docs"].get(r, {})
        ranks[str(r)] = {
            "generation": int(doc.get("generation", 0)),
            "wall_time": doc.get("wall_time"),
            "context": doc.get("context", {}),
            "stats": doc.get("stats", {}),
            "events": doc.get("events", []),
            "telemetry": res["streams"].get(r, []),
        }
    return {
        "format": FLEET_SECTION_FORMAT,
        "generation": int(res["generation"]),
        "world": [int(m) for m in res["world"]],
        "ranks_reporting": res["info"]["ranks"],
        "stale_dropped": res["info"]["stale_dropped"],
        "ranks": ranks,
        "aggregate": res["merged"],
        "skew_timeline": res["correlated"],
        "straggler_signal": res["signal"],
    }


def dump_fleet_blackbox(fleet_dir, reason="", aggregator=None, fleet=None,
                        last=200):
    """Persist ``<fleet_dir>/fleet-blackbox.json``: the PR 15 black-box
    document (format unchanged — every existing reader still validates
    it) EXTENDED with the cross-rank ``fleet`` section.  Pass the live
    ``aggregator`` for a fresh forced pass, or ``fleet`` to run a one-
    shot pass without one.  Returns the path (None when the package
    bridges are absent)."""
    if _tracing is None or _ckpt is None:
        return None
    if aggregator is None:
        if fleet is None:
            raise ValueError("dump_fleet_blackbox needs an aggregator "
                             "or a fleet handle")
        aggregator = FleetAggregator(fleet)
    res = aggregator.poll(force=True)
    doc = _tracing.blackbox_doc(reason=reason, last=last)
    doc["fleet"] = _fleet_section(res)
    # the corruption verdict rides beside the skew timeline: who
    # published what fingerprint, how every vote went, who is
    # permanently quarantined (read from disk, not from the aggregation
    # pass — the dying rank's last vote must survive its eviction)
    doc["fleet"]["corruption"] = read_integrity_dir(fleet_dir)
    path = fleet_blackbox_path(fleet_dir)
    with _ckpt.atomic_write(path, mode="w") as f:
        f.write(_strict_json(doc))
    if _telemetry is not None:
        _telemetry.counter("tracing.blackbox_dumps").inc()
    _tracing.emit("supervisor.blackbox", path=path, reason=str(reason))
    return path


def validate_fleet_section(doc, telemetry=None):
    """Raise ValueError unless ``doc`` (a black-box document) carries a
    schema-valid ``fleet`` section whose aggregation identity HOLDS:
    re-merging the stored per-rank telemetry at the section's
    generation must reproduce every aggregate counter exactly, and each
    merged counter's value must equal the sum of its own ``per_rank``
    breakdown.  ``telemetry`` (the standalone-loaded module) adds
    per-record schema validation of the aggregate when given."""
    fl = doc.get("fleet")
    if not isinstance(fl, dict):
        raise ValueError("black box has no 'fleet' section")
    if fl.get("format") != FLEET_SECTION_FORMAT:
        raise ValueError(f"unknown fleet-section format "
                         f"{fl.get('format')!r} (this build reads "
                         f"{FLEET_SECTION_FORMAT})")
    if not isinstance(fl.get("generation"), int):
        raise ValueError("fleet section missing int 'generation'")
    ranks = fl.get("ranks")
    if not isinstance(ranks, dict):
        raise ValueError("fleet section missing the 'ranks' object")
    for r, body in ranks.items():
        if not isinstance(body, dict) \
                or not isinstance(body.get("events"), list) \
                or not isinstance(body.get("telemetry"), list):
            raise ValueError(f"fleet section rank {r}: missing "
                             "events/telemetry lists")
    agg = fl.get("aggregate")
    if not isinstance(agg, list):
        raise ValueError("fleet section missing the 'aggregate' list")
    for field in ("skew_timeline",):
        if not isinstance(fl.get(field), list):
            raise ValueError(f"fleet section missing the {field!r} list")
    sig = fl.get("straggler_signal")
    if not isinstance(sig, dict) or "straggling" not in sig \
            or not isinstance(sig.get("rank"), int):
        raise ValueError("fleet section missing a straggler_signal "
                         "object with straggling/rank")
    corr = fl.get("corruption")
    if not isinstance(corr, dict):
        raise ValueError("fleet section missing the 'corruption' object")
    for field in ("fingerprints", "votes_by_rank", "quarantined"):
        if not isinstance(corr.get(field), dict):
            raise ValueError(f"corruption section missing the "
                             f"{field!r} object")
    cv = corr.get("verdict")
    if not isinstance(cv, dict) or not isinstance(cv.get("clean"), bool) \
            or not all(isinstance(cv.get(k), list) for k in
                       ("mismatch_steps", "suspected", "quarantined")):
        raise ValueError("corruption section missing a verdict object "
                         "with clean/mismatch_steps/suspected/quarantined")
    # the verdict must be derivable from the stored votes + quarantine
    # records — a black box claiming 'clean' over a disagreeing vote is
    # itself corrupt
    if cv["clean"] and (cv["mismatch_steps"] or cv["quarantined"]):
        raise ValueError("corruption verdict claims clean over recorded "
                         "mismatches/quarantines")
    for recs in corr["votes_by_rank"].values():
        if not isinstance(recs, list):
            raise ValueError("votes_by_rank values must be lists")
        for v in recs:
            if not isinstance(v, dict) or "agree" not in v \
                    or "step" not in v:
                raise ValueError(f"malformed vote record: {v!r}")
            if not v["agree"] and int(v["step"]) not in cv["mismatch_steps"]:
                raise ValueError(
                    f"vote at step {v['step']} disagreed but is absent "
                    f"from verdict.mismatch_steps")
    for entry in fl["skew_timeline"]:
        if not isinstance(entry, dict) \
                or not isinstance(entry.get("skew_seconds"), (int, float)) \
                or not isinstance(entry.get("slowest_rank"), int) \
                or not isinstance(entry.get("dominant_phase"), str):
            raise ValueError(f"malformed skew_timeline entry: {entry!r}")
    # the exactness invariant, re-checked from the document alone:
    # (a) every merged counter equals the sum of its per_rank breakdown
    for rec in agg:
        if telemetry is not None:
            telemetry.validate_record(rec)
        if rec.get("type") == "counter" and isinstance(
                rec.get("per_rank"), dict):
            total = sum(rec["per_rank"].values())
            if total != rec.get("value"):
                raise ValueError(
                    f"aggregation identity violated: {rec['name']} "
                    f"value {rec.get('value')} != per-rank sum {total}")
    # (b) re-merging the stored per-rank snapshots reproduces the
    # aggregate counters exactly (the end-to-end sum identity)
    streams = {int(r): body["telemetry"] for r, body in ranks.items()}
    remerged, _ = merge_streams(streams, generation=fl["generation"])
    want = {(r["name"], _labels_json(r)): r["value"]
            for r in agg if r.get("type") == "counter"}
    got = {(r["name"], _labels_json(r)): r["value"]
           for r in remerged if r.get("type") == "counter"}
    if want != got:
        diff = {k for k in set(want) | set(got)
                if want.get(k) != got.get(k)}
        raise ValueError(
            "aggregation identity violated: re-merging the per-rank "
            f"snapshots disagrees with the stored aggregate on {sorted(diff)}")
    return doc
