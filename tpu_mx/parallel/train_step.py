"""Compiled SPMD train step — the performance path (SURVEY §3.2's hot loop,
fused into ONE XLA program).

The reference's step is: CachedOp forward → autograd backward → KVStore
push/pull (NCCL/PS) → fused optimizer kernels, four engine-scheduled phases.
Here the entire step — forward, backward, gradient reduction (psum inserted
by XLA from the shardings), optimizer update, BN-stat update — is a single
jitted function with donated buffers, so weights never leave device and XLA
overlaps the collectives with the backward pass (the same overlap the
reference engineered via per-parameter engine ordering).

Sharding: parameters get PartitionSpecs from regex rules (default replicated
= pure DP; rules give Megatron-style TP or fsdp), batch enters sharded over
`dp` (and `sp` for sequence-parallel models).  Works mesh-less too (single
device jit).
"""
from __future__ import annotations

import functools
import logging
import os
import re
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import telemetry as _telemetry
from .. import tracing as _tracing
from ..ndarray import NDArray

__all__ = ["CompiledTrainStep", "fsdp_rules", "sharding_for", "apply_rules"]

_logger = logging.getLogger(__name__)


def _fingerprint_on():
    """``TPUMX_FINGERPRINT`` gates the device-side SDC fingerprint
    (ISSUE 20, parallel/integrity.py; default ON).  Read at trace time:
    flipping it changes the program, which the overhead-receipt A/B does
    by construction (one fresh process per arm)."""
    return os.environ.get("TPUMX_FINGERPRINT", "1").lower() \
        not in ("0", "false", "off")


def _shape_signature(raw):
    """The batch's shape signature (``"float32[16,4];float32[16]"``) —
    the label the per-shape compile metrics key on (ISSUE 14): jax
    retraces/compiles once per distinct operand signature even when the
    jit wrapper itself survives, so "how many programs did this run
    compile, for which shapes, costing how long" needs the signature as
    the series key, not just the build count."""
    parts = []
    for b in raw:
        if b is None:
            parts.append("none")
            continue
        dt = np.dtype(getattr(b, "dtype", np.float32)).name
        shape = ",".join(str(int(d)) for d in getattr(b, "shape", ()))
        parts.append(f"{dt}[{shape}]")
    return ";".join(parts)


def apply_rules(name, shape, rules, mesh):
    """First matching (regex → PartitionSpec) rule wins; axes not in the mesh
    are dropped from the spec; default replicated."""
    if rules:
        for pattern, spec in rules:
            if re.search(pattern, name):
                cleaned = tuple(
                    (ax if (ax is not None and ax in mesh.axis_names) else None)
                    for ax in spec) if mesh is not None else ()
                # drop trailing Nones beyond rank
                cleaned = cleaned[:len(shape)]
                return P(*cleaned)
    return P()


def sharding_for(mesh, spec):
    return NamedSharding(mesh, spec) if mesh is not None else None


class CompiledTrainStep:
    """One-program train step over an (optional) mesh.

    net        — an initialized HybridBlock (run one forward first)
    loss_fn    — gluon Loss block (operates on raw arrays through F ops)
    optimizer  — tpu_mx optimizer (its pure update_core is traced in)
    mesh       — jax.sharding.Mesh or None
    rules      — [(regex, PartitionSpec)] parameter sharding rules
    data_specs — PartitionSpecs for the batch inputs (default P('dp') on axis0)
    n_loss_args — how many TRAILING step() args go to the loss instead of
                  the network forward (default 1: the label; 2 for e.g.
                  (label, sample_weight) losses)
    gradient_compression — None, or {"type": "2bit", "threshold": t} /
                  {"type": "int8"}: the in-step quantized gradient
                  allreduce (SURVEY §2.3 stretch; the reference compressed
                  only on the kvstore push wire,
                  REF:src/kvstore/gradient_compression.cc).  Per-device
                  partial gradients are quantized with per-device error
                  feedback (carried in the train state, dp-sharded), summed
                  with a psum over `dp`, and dequantized into the optimizer.
                  Requires a mesh with dp>1 and pure-DP (replicated) params.
    accum_steps — gradient accumulation: every K-th step() applies the
                  optimizer with the MEAN of the last K microbatch
                  gradients (the reference's grad_req='add' + delayed
                  Trainer.step pattern, REF:python/mxnet/gluon/trainer.py).
                  Two compiled programs (accumulate / apply) — static
                  control flow stays outside jit.  BN stats still update
                  every microbatch.
    """

    def __init__(self, net, loss_fn, optimizer, mesh=None, rules=None,
                 data_specs=None, donate=True, n_loss_args=1,
                 gradient_compression=None, accum_steps=1):
        self.net = net
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        params = {k: p for k, p in net.collect_params().items()
                  if p._data is not None}
        if not params:
            raise ValueError("net has no initialized parameters; run one "
                             "forward pass before compiling the step")
        self._params = params
        self._diff_keys = [
            k for k, p in params.items()
            if p.grad_req != "null" and jnp.issubdtype(p.data().dtype,
                                                       jnp.floating)]
        self._lr_mults = {k: params[k].lr_mult for k in self._diff_keys}
        self._wd_mults = {k: params[k].wd_mult for k in self._diff_keys}
        self.values = {k: p.data()._data for k, p in params.items()}
        # mixed precision: f32 master copies for low-precision diff params
        # (the reference's mp_* kernel family; optimizer.multi_precision)
        self._mp_keys = set()
        if getattr(optimizer, "multi_precision", False):
            self._mp_keys = {
                k for k in self._diff_keys
                if self.values[k].dtype in (jnp.float16, jnp.bfloat16)}
        self.masters = {k: self.values[k].astype(jnp.float32)
                        for k in self._mp_keys}
        self.opt_states = {
            k: optimizer.create_state(
                i, NDArray(self.masters[k]) if k in self._mp_keys
                else params[k].data())
            for i, k in enumerate(self._diff_keys)}
        self._t = 0
        self._specs = {k: apply_rules(k, v.shape, rules, mesh)
                       for k, v in self.values.items()}
        self._data_specs = data_specs
        self._donate = donate
        if n_loss_args < 1:
            raise ValueError("n_loss_args must be >= 1 (the label)")
        self._n_loss_args = n_loss_args
        if accum_steps < 1:
            raise ValueError("accum_steps must be >= 1")
        # accum × compression composes as compress-ONCE-per-applied-update:
        # microbatch grads accumulate per-device (dp-sharded local buffers,
        # no collective), and the single quantized psum happens in the
        # apply step on the accumulated mean — one quantization error per
        # update, exactly one compressed reduction (closes DIVERGENCES'
        # former #12 rejection)
        self._accum = int(accum_steps)
        self._micro = 0
        self._last_fp = None  # last committed step's device fingerprint
        self._gacc = None     # lazy f32 grad-accumulation buffers
        self._accum_jit = None
        self._compression = None
        self._efs = {}
        if gradient_compression:
            ctype = gradient_compression.get("type", "2bit")
            if ctype not in ("2bit", "int8", "fp8"):
                raise ValueError(f"unsupported compression type {ctype!r} "
                                 "(have: 2bit, int8, fp8)")
            if mesh is None or "dp" not in mesh.axis_names or \
                    mesh.shape["dp"] < 2:
                raise ValueError(
                    "gradient_compression needs a mesh with a dp axis >1 "
                    "(it compresses the dp gradient reduction)")
            sharded = [k for k in self._diff_keys
                       if any(ax is not None for ax in self._specs[k])]
            if sharded:
                raise ValueError(
                    "gradient_compression supports pure-DP (replicated) "
                    f"params; these are sharded: {sharded[:3]}...")
            # the compressed reduce psums over 'dp' only; batch sharding
            # over any other axis would silently drop those contributions
            bad_axes = set()
            for spec in (data_specs or ()):
                for ax in spec:
                    for a in (ax if isinstance(ax, tuple) else (ax,)):
                        if a is not None and a != "dp":
                            bad_axes.add(a)
            if bad_axes:
                raise ValueError(
                    "gradient_compression reduces over 'dp' only, but "
                    f"data_specs shard the batch over {sorted(bad_axes)}")
            self._compression = dict(gradient_compression, type=ctype)
            ndp = mesh.shape["dp"]
            # per-device quantization error feedback, dp-sharded on axis 0;
            # allocated ALREADY sharded (out_shardings) so a big model never
            # materializes ndp full copies on one device — one compile for
            # the whole dict, not one per tensor
            ef_sh = sharding_for(mesh, P("dp"))
            shapes = {k: self.values[k].shape for k in self._diff_keys}
            alloc = jax.jit(
                lambda: {k: jnp.zeros((ndp,) + s, jnp.float32)
                         for k, s in shapes.items()},
                out_shardings={k: ef_sh for k in shapes})
            self._efs = alloc()
        self._jitted = None
        self._build_count = 0
        # batch shape-signatures already traced/compiled: the first step
        # at a NEW signature pays the retrace+XLA-compile inside its jit
        # call, so that call's wall clock is observed as compile_seconds
        # under the signature label (ISSUE 14 capacity twins)
        self._seen_signatures = set()
        # zombie-step guard: a watchdog-abandoned step that later finishes
        # must not apply its (stale) result over restored state.  Restores
        # bump _generation under _state_lock; _step commits its new state
        # only if the generation it started under is still current.
        self._state_lock = threading.Lock()
        self._generation = 0

    # -- sharding helpers -----------------------------------------------------
    def _value_shardings(self):
        return {k: sharding_for(self.mesh, self._specs[k])
                for k in self.values}

    def _state_shardings(self):
        return {
            k: jax.tree_util.tree_map(
                lambda _: sharding_for(self.mesh, self._specs[k]),
                self.opt_states[k])
            for k in self._diff_keys}

    def place(self):
        """Device_put params/opt state onto their mesh shardings."""
        if self.mesh is None:
            return
        vs = self._value_shardings()
        values = {k: jax.device_put(v, vs[k])
                  for k, v in self.values.items()}
        masters = {k: jax.device_put(v, vs[k])
                   for k, v in self.masters.items()}
        ss = self._state_shardings()
        opt_states = {k: jax.device_put(s, ss[k])
                      for k, s in self.opt_states.items()}
        ef_sh = sharding_for(self.mesh, P("dp"))
        efs = {k: jax.device_put(v, ef_sh)
               for k, v in self._efs.items()}
        # publish under the state lock: a watchdog-abandoned step's late
        # result application (gated by _stale under this lock) must never
        # interleave with re-placement of restored weights
        with self._state_lock:
            self.values, self.masters = values, masters
            self.opt_states, self._efs = opt_states, efs

    # -- the compiled program -------------------------------------------------
    def _build(self, n_batch_args):
        # every _build is a fresh jit program (first compile, or a batch-
        # arity change invalidating the old one) — the recompile-storm
        # signal ops dashboards watch (docs/observability.md).  Counted at
        # ENTRY so a watchdog that times out during a long compile sees
        # the counter already moved and grants compile grace
        # (supervisor.run_with_deadline's grace_signal).
        self._build_count += 1
        _telemetry.counter("train_step.recompiles").inc()
        net, loss_fn, opt = self.net, self.loss_fn, self.optimizer
        diff_keys = list(self._diff_keys)
        lr_mults, wd_mults = self._lr_mults, self._wd_mults
        base_wd = opt.wd

        mp_keys = set(self._mp_keys)

        n_loss = self._n_loss_args
        compression = self._compression
        mesh = self.mesh

        # Fused flat update (single-chip, TPUMX_FUSED_UPDATE=1 opt-in):
        # params with identical elementwise update programs — same (mp,
        # dtype, lr_mult, wd_mult, state structure) — are concatenated
        # into ONE flat buffer, updated in one optimizer call, and sliced
        # back.  Measured on the r4 chip for ResNet-50/SGD-mom: the
        # concat+slice round trip costs MORE than the ~160 per-param
        # op-clusters it replaces (2341.8 vs 2379.2 img/s) because the
        # step is HBM-bandwidth-bound (PROFILE_STEP_r04.json) and the
        # flat buffers add a full extra pass over masters+grads+state.
        # Default OFF; kept because op-overhead-bound models (many tiny
        # params) are the case it does help, and the equivalence is
        # regression-tested (bit-identical to the per-param path).
        # Sharded/multi-chip params always keep the per-param path
        # (flattening would destroy their shardings); LAMB-style
        # optimizers are excluded by the elementwise_update flag.
        fuse_groups = []
        if mesh is None and getattr(opt, "elementwise_update", False) and \
                os.environ.get("TPUMX_FUSED_UPDATE", "0") == "1":
            by_sig = {}
            for k in diff_keys:
                w = self.masters[k] if k in mp_keys else self.values[k]
                leaves, treedef = jax.tree_util.tree_flatten(
                    self.opt_states[k])
                if not all(getattr(l, "shape", None) == w.shape
                           for l in leaves):
                    continue
                sig = (k in mp_keys, str(self.values[k].dtype),
                       str(w.dtype), lr_mults[k], wd_mults[k],
                       str(treedef), tuple(str(l.dtype) for l in leaves))
                by_sig.setdefault(sig, []).append(k)
            fuse_groups = [ks for ks in by_sig.values() if len(ks) > 1]
        fused_keys = {k for ks in fuse_groups for k in ks}
        self._fuse_groups = fuse_groups  # introspection (tests/debug)

        def make_lfn(const_vals, key, data_args, loss_args):
            def lfn(dv):
                pm = dict(const_vals)
                pm.update(dv)
                out, updates = net._functional_call(pm, key, True, data_args)
                if isinstance(out, (tuple, list)):
                    # multi-output nets: the step trains on the FIRST
                    # output only.  That silently drops e.g. an MoE aux
                    # loss unless the net folds it into output[0] (the
                    # loss-in-forward + PassThrough pattern) — warn once
                    # per build so the dropped term is never invisible.
                    from ..gluon.loss import PassThrough
                    if not isinstance(loss_fn, PassThrough):
                        _logger.warning(
                            "CompiledTrainStep: net returned %d outputs; "
                            "training on output[0] and DROPPING the rest "
                            "(an MoE aux loss would be lost — fold extra "
                            "terms into the objective in forward() and "
                            "use gluon.loss.PassThrough)", len(out))
                    out = out[0]
                l = loss_fn(out, *loss_args)
                return jnp.mean(l), updates
            return lfn

        def shard_dspecs(batch):
            return self._data_specs or tuple(P("dp")
                                             for _ in range(len(batch)))

        def shard_fwd_grads(dv, cv, key, b_local):
            """Shared per-shard preamble of the compressed accumulate AND
            apply programs: per-device key fold, forward+grad on the local
            batch shard, loss/BN-updates pmean'd.  Keeping it single-copy
            keeps the two programs numerically in lockstep (the compress-
            once equivalence depends on it)."""
            key = jax.random.fold_in(key, jax.lax.axis_index("dp"))
            dat, lar = b_local[:-n_loss], b_local[-n_loss:]
            (loss, updates), grads = jax.value_and_grad(
                make_lfn(cv, key, dat, lar), has_aux=True)(dv)
            loss = jax.lax.pmean(loss, "dp")
            updates = {uk: jax.lax.pmean(uv, "dp")
                       for uk, uv in updates.items()}
            return loss, updates, grads

        def compressed_grads(diff_vals, const_vals, efs, key, batch,
                             gacc=None):
            """shard_map over dp: each device takes partial grads on its
            batch shard, quantizes them with its own error feedback, and
            the reduction is a psum of the QUANTIZED values (the EQuARX-
            style in-collective compression the reference could only do on
            the kvstore wire)."""
            from jax.experimental.shard_map import shard_map
            from ..contrib.compression import (quantize_2bit_core,
                                               quantize_fp8_core,
                                               quantize_int8_core)

            ndp = mesh.shape["dp"]
            ctype = compression["type"]
            threshold = float(compression.get("threshold", 0.5))
            dspecs = shard_dspecs(batch)

            def per_shard(dv, cv, efs_l, gacc_l, key, *b_local):
                loss, updates, grads = shard_fwd_grads(dv, cv, key, b_local)
                red, new_efs = {}, {}
                for k in diff_keys:
                    g = grads[k].astype(jnp.float32)
                    if gacc is not None:
                        # compress-once-per-update: fold the final
                        # microbatch into the LOCAL accumulated mean; the
                        # quantized psum below is the update's only
                        # collective and only quantization
                        g = g / K + gacc_l[k][0]
                    ef = efs_l[k][0]
                    if ctype == "2bit":
                        deq, new_ef = quantize_2bit_core(g, ef, threshold)
                    elif ctype == "fp8":
                        deq, new_ef = quantize_fp8_core(g, ef)
                    else:
                        deq, new_ef = quantize_int8_core(g, ef)
                    red[k] = jax.lax.psum(deq, "dp") / ndp
                    new_efs[k] = new_ef[None]
                return loss, red, new_efs, updates

            gacc_arg = gacc if gacc is not None else \
                {k: jnp.zeros((ndp,) + (1,) * diff_vals[k].ndim,
                              jnp.float32) for k in diff_keys}
            fn = shard_map(
                per_shard, mesh=mesh,
                in_specs=(P(), P(), P("dp"), P("dp"), P()) + tuple(dspecs),
                out_specs=(P(), P(), P("dp"), P()), check_rep=False)
            return fn(diff_vals, const_vals, efs, gacc_arg, key, *batch)

        K = self._accum

        def grads_and_updates(values, key, batch):
            """Shared by the apply and accumulate programs: forward+grad
            over the diff params, plus the BN-stat aux updates applied to
            a copy of `values`."""
            data_args, loss_args = batch[:-n_loss], batch[-n_loss:]
            diff_vals = {k: values[k] for k in diff_keys}
            const_vals = {k: v for k, v in values.items()
                          if k not in set(diff_keys)}
            (loss, updates), grads = jax.value_and_grad(
                make_lfn(const_vals, key, data_args, loss_args),
                has_aux=True)(diff_vals)
            new_vals = dict(values)
            for k, v in updates.items():
                if k in new_vals:
                    new_vals[k] = v.astype(new_vals[k].dtype)
            return loss, grads, new_vals

        def fn(values, masters, opt_states, efs, gacc, t, lr, key, *batch):
            if compression:
                diff_vals = {k: values[k] for k in diff_keys}
                const_vals = {k: v for k, v in values.items()
                              if k not in set(diff_keys)}
                loss, grads, new_efs, updates = compressed_grads(
                    diff_vals, const_vals, efs, key, batch,
                    gacc=gacc if K > 1 else None)
                aux_vals = dict(values)
                for k, v in updates.items():
                    if k in aux_vals:
                        aux_vals[k] = v.astype(aux_vals[k].dtype)
            else:
                loss, grads, aux_vals = grads_and_updates(values, key, batch)
                new_efs = efs
            if K > 1 and not compression:
                # fold the final microbatch into the accumulated mean
                grads = {k: grads[k].astype(jnp.float32) / K + gacc[k]
                         for k in diff_keys}
                new_gacc = {k: jnp.zeros_like(v) for k, v in gacc.items()}
            elif K > 1:
                # compression already folded gacc inside the shard_map
                new_gacc = {k: jnp.zeros_like(v) for k, v in gacc.items()}
            else:
                new_gacc = gacc
            new_vals = aux_vals  # starts from the BN-stat-updated copy
            new_masters = {}
            new_states = {}
            for ks in fuse_groups:
                is_mp = ks[0] in mp_keys
                srcs = [masters[k] if is_mp else values[k] for k in ks]
                flat_w = jnp.concatenate([s.ravel() for s in srcs])
                flat_g = jnp.concatenate(
                    [grads[k].astype(flat_w.dtype).ravel() for k in ks])
                leaves0, st_def = jax.tree_util.tree_flatten(
                    opt_states[ks[0]])
                flat_state = jax.tree_util.tree_unflatten(st_def, [
                    jnp.concatenate(
                        [jax.tree_util.tree_flatten(opt_states[k])[0][i]
                         .ravel() for k in ks])
                    for i in range(len(leaves0))])
                w, s = opt.update_core(
                    flat_w, flat_g, flat_state, lr * lr_mults[ks[0]],
                    base_wd * wd_mults[ks[0]], t)
                s_leaves, s_def = jax.tree_util.tree_flatten(s)
                off = 0
                for k, src in zip(ks, srcs):
                    n = src.size
                    piece = w[off:off + n].reshape(src.shape)
                    if is_mp:
                        new_masters[k] = piece
                    new_vals[k] = piece.astype(values[k].dtype)
                    new_states[k] = jax.tree_util.tree_unflatten(
                        s_def,
                        [sl[off:off + n].reshape(src.shape)
                         for sl in s_leaves])
                    off += n
            for k in diff_keys:
                if k in fused_keys:
                    continue
                if k in mp_keys:
                    # update in f32 master space; forward weight is a cast
                    w, s = opt.update_core(
                        masters[k], grads[k].astype(jnp.float32),
                        opt_states[k], lr * lr_mults[k],
                        base_wd * wd_mults[k], t)
                    new_masters[k] = w
                    new_vals[k] = w.astype(values[k].dtype)
                else:
                    # match the param dtype regardless of path (the K>1
                    # fold and compression accumulate in f32)
                    w, s = opt.update_core(values[k],
                                           grads[k].astype(values[k].dtype),
                                           opt_states[k],
                                           lr * lr_mults[k],
                                           base_wd * wd_mults[k], t)
                    new_vals[k] = w.astype(values[k].dtype)
                new_states[k] = s
            # device-side SDC fingerprint (ISSUE 20, parallel/integrity.py):
            # folded over the POST-UPDATE parameter tree INSIDE the same
            # program that applied it, read back beside the loss — the hot
            # path stays one program, and dp replicas (bit-identical
            # post-AllReduce) must produce the same digest.  Off → a
            # constant uint32(0): same output arity, XLA folds it away
            # (the overhead A/B's baseline arm).
            if _fingerprint_on():
                from .integrity import device_fingerprint
                fp = device_fingerprint(new_vals)
            else:
                fp = jnp.uint32(0)
            return (new_vals, new_masters, new_states, new_efs, new_gacc,
                    loss, fp)

        def accum_fn(values, gacc, key, *batch):
            """Microbatch accumulate: grads/K into the f32 buffers, BN-stat
            aux updates applied, NO optimizer step."""
            loss, grads, new_vals = grads_and_updates(values, key, batch)
            new_gacc = {k: gacc[k] + grads[k].astype(jnp.float32) / K
                        for k in diff_keys}
            return new_vals, new_gacc, loss

        def compressed_accum_fn(values, gacc, key, *batch):
            """Microbatch accumulate under compression: per-shard LOCAL
            grads/K into dp-sharded (ndp, ...) buffers — NO collective and
            NO quantization here; both happen exactly once in the apply
            step (compress-once-per-update).  BN aux updates are pmean'd
            and applied every microbatch as usual."""
            from jax.experimental.shard_map import shard_map
            diff_vals = {k: values[k] for k in diff_keys}
            const_vals = {k: v for k, v in values.items()
                          if k not in set(diff_keys)}
            dspecs = shard_dspecs(batch)

            def per_shard(dv, cv, gacc_l, key, *b_local):
                loss, updates, grads = shard_fwd_grads(dv, cv, key, b_local)
                new_gacc = {
                    k: gacc_l[k] + grads[k].astype(jnp.float32)[None] / K
                    for k in diff_keys}
                return loss, new_gacc, updates

            sm = shard_map(
                per_shard, mesh=mesh,
                in_specs=(P(), P(), P("dp"), P()) + tuple(dspecs),
                out_specs=(P(), P("dp"), P()), check_rep=False)
            loss, new_gacc, updates = sm(diff_vals, const_vals, gacc, key,
                                         *batch)
            new_vals = dict(values)
            for k, v in updates.items():
                if k in new_vals:
                    new_vals[k] = v.astype(new_vals[k].dtype)
            return new_vals, new_gacc, loss

        def alloc_gacc(shardings=None):
            if K <= 1 or self._gacc is not None:
                return
            lead = (mesh.shape["dp"],) if (compression and mesh is not None) \
                else ()
            shapes = {k: lead + self.values[k].shape
                      for k in self._diff_keys}
            # tpumx-lint: disable=concurrency -- first-build-only init:
            # runs before any step result exists that a restore could
            # race, and fresh zeros are the correct post-restore value
            self._gacc = jax.jit(
                lambda: {k: jnp.zeros(s, jnp.float32)
                         for k, s in shapes.items()},
                **({"out_shardings": shardings} if shardings else {}))()

        donate = (0, 1, 2, 3, 4) if self._donate else ()
        if self.mesh is None:
            self._jitted = jax.jit(fn, donate_argnums=donate)
            if K > 1:
                self._accum_jit = jax.jit(
                    accum_fn, donate_argnums=(0, 1) if self._donate else ())
                alloc_gacc()
            return
        repl = sharding_for(self.mesh, P())
        dspecs = self._data_specs or tuple(P("dp") for _ in range(n_batch_args))
        batch_sh = tuple(sharding_for(self.mesh, s) for s in dspecs)
        master_sh = {k: sharding_for(self.mesh, self._specs[k])
                     for k in self._mp_keys}
        efs_sh = {k: sharding_for(self.mesh, P("dp")) for k in self._efs}
        # under compression the accumulation buffers are per-device LOCAL
        # rows, dp-sharded on their leading axis (like the error feedback)
        gacc_spec = P("dp") if compression else None
        gacc_sh = {k: sharding_for(self.mesh,
                                   gacc_spec or self._specs[k])
                   for k in (self._diff_keys if K > 1 else [])}
        in_sh = (self._value_shardings(), master_sh, self._state_shardings(),
                 efs_sh, gacc_sh, repl, repl, repl) + batch_sh
        out_sh = (self._value_shardings(), master_sh, self._state_shardings(),
                  efs_sh, gacc_sh, repl, repl)
        self._jitted = jax.jit(
            fn, in_shardings=in_sh, out_shardings=out_sh,
            donate_argnums=donate)
        if K > 1:
            self._accum_jit = jax.jit(
                compressed_accum_fn if compression else accum_fn,
                in_shardings=(self._value_shardings(), gacc_sh, repl)
                + batch_sh,
                out_shardings=(self._value_shardings(), gacc_sh, repl),
                donate_argnums=(0, 1) if self._donate else ())
            alloc_gacc(gacc_sh)

    @property
    def recompiles(self):
        """How many jit programs THIS instance has built (the global
        recompile-storm counter is `train_step.recompiles` in telemetry)."""
        return self._build_count

    def fingerprint(self):
        """The last committed step's post-update parameter fingerprint as
        a Python int (ISSUE 20, parallel/integrity.py), or None before
        the first applied update or with ``TPUMX_FINGERPRINT=0``.

        The uint32 digest was computed INSIDE the fused step and committed
        as a lazy device scalar; the conversion here rides on a program
        that already completed (the sentinel read its loss), so this is a
        cheap transfer at the step boundary — the IntegrityMonitor's
        ``fingerprint_fn`` seam."""
        if not _fingerprint_on():
            return None
        fp = self._last_fp
        if fp is None:
            return None
        # tpumx-lint: disable=sync-point -- the digest readback rides a
        # step whose loss was already read (program complete); called at
        # the K-step vote cadence, never inside the dispatch path
        return int(jax.device_get(fp))

    def step(self, *batch, lr=None, deadline=None, compile_grace=120.0):
        """Run one step; batch = (*data_args, label) as NDArray/array.

        ``deadline=`` arms the hung-step watchdog (tpu_mx/supervisor.py):
        the dispatch AND the loss readback run on a daemon thread joined
        with the deadline, so a stalled collective — which jax's async
        dispatch would otherwise surface as an eternal hang at the first
        device read — raises a catchable ``WatchdogTimeout``
        (a ``WorkerFailure``) instead.  The deadline is recompile-aware:
        when a jit (re)build starts during the step, the watchdog grants
        ``compile_grace`` extra seconds once rather than killing a
        legitimate compile."""
        if deadline is not None:
            from ..supervisor import run_with_deadline
            gen0 = self._generation

            def call():
                loss = self._step(batch, lr, expect_gen=gen0)
                # force the async dispatch to completion INSIDE the
                # watchdog thread — a hung collective parks here
                t_read = time.perf_counter()
                jax.block_until_ready(loss._data)
                _tracing.emit("train_step.phase", t0=t_read,
                              t1=time.perf_counter(),
                              phase="loss_readback")
                return loss

            count0 = self._build_count
            return run_with_deadline(
                call, deadline, name="train_step",
                grace=compile_grace or 0.0,
                grace_signal=lambda: self._build_count - count0,
                message=f"train_step hung past its {deadline:.1f}s "
                        "deadline (stalled collective or device); restart "
                        "from the last checkpoint")
        return self._step(batch, lr)

    def _step(self, batch, lr, expect_gen=None):
        from .. import random as _random
        if expect_gen is None:
            # capture at entry: even un-watchdogged calls (the supervisor's
            # sup.step(lambda: step.step(*batch)) path runs THIS method on
            # the watchdog thread) discard their result if a restore
            # supersedes them mid-flight
            expect_gen = self._generation
        t_start = time.perf_counter()
        # chaos straggler injection (ISSUE 18): the slow_worker delay
        # must land INSIDE the data_wait window below — an injected
        # straggler whose delay fell outside every measured phase would
        # be invisible to the cross-rank phase attribution that is the
        # point of injecting it (tpu_mx/parallel/fleet_obs.py)
        from ..contrib import chaos as _chaos
        _chaos.maybe_slow_worker()
        # None batch args pass through (optional model inputs like
        # valid_length); they contribute no leaves to the jitted
        # signature.  Non-NDArray operands stay RAW (numpy/python): the
        # jit boundary commits them on the C++ fast path — an eager
        # jnp.asarray here costs a dispatch per operand per step (the
        # PR-9 decode cliff; hot-path-purity flags it now)
        raw = tuple(b._data if isinstance(b, NDArray) else b
                    for b in batch)
        # flight-recorder phase events (docs/observability.md): the step
        # histogram split into its host-side stations — the device-side
        # forward+backward+optimizer is ONE XLA program, so "dispatch"
        # covers its (async) enqueue and "loss_readback" (emitted at the
        # read sites) the block on its result
        t_data = time.perf_counter()
        _tracing.emit("train_step.phase", t0=t_start, t1=t_data,
                      phase="data_wait")
        if self._jitted is None:
            self._build(len(raw))
            self.place()
            _tracing.emit("train_step.phase", t0=t_data,
                          t1=time.perf_counter(), phase="recompile")
        # per-shape-signature compile accounting (ISSUE 14): the first
        # step at a new operand signature pays jax's retrace + XLA
        # compile inside the jit call below — count it under the
        # signature label and observe that call's wall clock as the
        # compile cost.  Steady-state steps pay one set lookup.
        sig = _shape_signature(raw)
        fresh_sig = sig not in self._seen_signatures
        if fresh_sig:
            self._seen_signatures.add(sig)
            _telemetry.counter("train_step.compiles", signature=sig).inc()
        t_compile = time.perf_counter()
        key = _random.take_key()
        if self._accum > 1 and self._micro < self._accum - 1:
            # microbatch: accumulate grads, no optimizer application
            t_disp = time.perf_counter()
            new_vals, new_gacc, loss = self._accum_jit(
                self.values, self._gacc, key, *raw)
            _tracing.emit("train_step.phase", t0=t_disp,
                          t1=time.perf_counter(), phase="dispatch")
            if fresh_sig:
                _telemetry.histogram(
                    "train_step.compile_seconds", signature=sig).observe(
                        time.perf_counter() - t_compile)
            with self._state_lock:
                if self._stale(expect_gen):
                    return NDArray(loss)
                self.values, self._gacc = new_vals, new_gacc
                self._micro += 1
            self._record_step(raw, t_start)
            return NDArray(loss)
        t_next = self._t + 1
        if lr is None:
            sched = self.optimizer.lr_scheduler
            lr = sched(t_next) if sched else self.optimizer.lr
        gacc = self._gacc if self._accum > 1 else {}
        t_disp = time.perf_counter()
        # np scalars, not jnp.asarray: the jit boundary places them —
        # two fewer eager device commits per step
        (new_vals, new_masters, new_states, new_efs, gacc,
         loss, fp) = self._jitted(
            self.values, self.masters, self.opt_states, self._efs, gacc,
            np.float32(t_next), np.float32(lr),
            key, *raw)
        t_done = time.perf_counter()
        _tracing.emit("train_step.phase", t0=t_disp, t1=t_done,
                      phase="dispatch")
        if fresh_sig:
            _telemetry.histogram(
                "train_step.compile_seconds", signature=sig).observe(
                    t_done - t_compile)
        with self._state_lock:
            if self._stale(expect_gen):
                return NDArray(loss)
            (self.values, self.masters, self.opt_states,
             self._efs) = new_vals, new_masters, new_states, new_efs
            # the step's device fingerprint commits WITH the state it
            # digests (still a lazy device scalar — fingerprint() is
            # where the int conversion happens, off the hot path)
            self._last_fp = fp
            self._t = t_next
            self._micro = 0
            if self._accum > 1:
                self._gacc = gacc
        # the optimizer's device work is inside the fused program; this
        # phase is the host-side commit of its result (the new train
        # state becoming THE state, under the zombie-step lock)
        _tracing.emit("train_step.phase", t0=t_done,
                      t1=time.perf_counter(), phase="optimizer_update")
        # chaos SDC injection (ISSUE 20): flip one bit of the COMMITTED
        # state, after this step's fingerprint was computed — the flip is
        # silent until the NEXT published fingerprint disagrees, which is
        # the detection latency the defense actually promises (≤ K steps)
        bit = _chaos.maybe_bitflip()
        if bit is not None:
            self._apply_bitflip(bit)
        self._record_step(raw, t_start)
        return NDArray(loss)

    def _apply_bitflip(self, bit):
        """Flip bit ``bit`` of element 0 of the first diff param — the
        chaos ``bitflip_param_at_step`` / ``bitflip_grad_rank`` payload.
        Mixed-precision keys flip the f32 MASTER: the forward weight is
        recast from it on every update, so flipping only the cast copy
        would silently self-heal one step later."""
        key = self._diff_keys[0]
        with self._state_lock:
            use_master = key in self._mp_keys
            tree = self.masters if use_master else self.values
            # tpumx-lint: disable=sync-point,hot-path-purity -- chaos
            # fault INJECTION (test-only, armed by TPUMX_CHAOS): the
            # whole point is to corrupt committed state; the roundtrip
            # fires at most once per run and never in production
            host = np.array(jax.device_get(tree[key]))
            flat = host.reshape(-1)
            view = flat.view(np.uint32) if flat.dtype == np.float32 \
                else flat.view(np.uint8)
            nbits = view.dtype.itemsize * 8
            view[0] ^= view.dtype.type(1 << (int(bit) % nbits))
            if self.mesh is not None:
                tree[key] = jax.device_put(
                    host, sharding_for(self.mesh, self._specs[key]))
            else:
                tree[key] = jnp.asarray(host)
        _logger.debug("train_step: chaos bit-flip applied to %r bit %d "
                      "(%s)", key, int(bit),
                      "master" if use_master else "value")

    def _stale(self, expect_gen):
        """True when the train state was restored (generation bumped) while
        this step ran past its watchdog deadline on an abandoned thread —
        the stale result must be DISCARDED, not applied over the restored
        weights (call with _state_lock held)."""
        if expect_gen is not None and self._generation != expect_gen:
            _logger.warning(
                "train_step: discarding a stale step result — the train "
                "state was restored while this step ran past its watchdog "
                "deadline")
            return True
        return False

    @staticmethod
    def _record_step(raw, t_start):
        """Per-step telemetry: host-side dispatch latency (jax dispatch is
        async, so this is queue latency — steady-state it converges to the
        device step time because the dispatch queue applies backpressure),
        step count, and the examples/sec gauge from the batch leading dim."""
        dt = time.perf_counter() - t_start
        _telemetry.counter("train_step.steps").inc()
        _telemetry.histogram("train_step.seconds").observe(dt)
        n = next((b.shape[0] for b in raw
                  if b is not None and getattr(b, "ndim", 0)), None)
        if n and dt > 0:
            _telemetry.gauge("train_step.examples_per_sec").set(n / dt)

    def sync_to_net(self):
        """Write device weights back into the Gluon parameters (for eval,
        checkpointing through net.save_parameters, etc.)."""
        for k, p in self._params.items():
            p._data._rebind(self.values[k])

    def sync_from_net(self):
        """Inverse of `sync_to_net`: reload the device weights from the
        Gluon parameters — the rollback path after `elastic.auto_resume`
        restored `net` from a checkpoint, without rebuilding the jit
        program.  Values are COPIED (donation would otherwise delete the
        params' live buffers on the next step), masters re-derived from
        the restored values, and in-flight gradient accumulation dropped
        (partial grads against the old weights are invalid).  Optimizer
        state is deliberately kept: the Gluon net carries none — restore
        it via `load_state_dict`/`load_checkpoint` when exactness
        matters."""
        values = {k: jnp.copy(p.data()._data)
                  for k, p in self._params.items()}
        masters = {k: values[k].astype(jnp.float32)
                   for k in self._mp_keys}
        if self.mesh is not None:
            vs = self._value_shardings()
            values = {k: jax.device_put(v, vs[k])
                      for k, v in values.items()}
            masters = {k: jax.device_put(v, vs[k])
                       for k, v in masters.items()}
        with self._state_lock:
            self._generation += 1  # invalidate any watchdog-abandoned step
            self.values = values
            self.masters = masters
            self._reset_accumulation()

    def aot_compiled(self, *batch):
        """Lower + compile the step WITHOUT executing it and return the
        jax Compiled object (for cost_analysis / memory_analysis / HLO
        text).  Shares the jit/persistent compile cache with step(), so
        after a step() has run this is cache-hit cheap.  Used by bench.py
        (XLA-cost MFU is the number-of-record, VERDICT r4 ask#9) and
        tools/mfu_probe.py."""
        raw = tuple(b._data if isinstance(b, NDArray)
                    else (None if b is None else jnp.asarray(b))
                    for b in batch)
        if self._jitted is None:
            self._build(len(raw))
            self.place()
        # a constant key: lowering only needs the shape/dtype, and an
        # introspection helper must not advance the global RNG stream
        # (that would silently change later dropout masks)
        # tpumx-lint: disable=determinism -- lowering only needs shape/dtype
        key = jax.random.PRNGKey(0)
        gacc = self._gacc if self._accum > 1 else {}
        lowered = self._jitted.lower(
            self.values, self.masters, self.opt_states, self._efs, gacc,
            jnp.asarray(float(self._t or 1), jnp.float32),
            jnp.asarray(self.optimizer.lr or 0.1, jnp.float32),
            key, *raw)
        return lowered.compile()

    def state_dict(self):
        """Snapshot of the train state.  Leaves are COPIED: with buffer
        donation active (the default), later step() calls delete the live
        arrays — a snapshot that aliased them would die with them."""
        copy = functools.partial(jax.tree_util.tree_map, jnp.copy)
        sd = {"values": copy(self.values), "masters": copy(self.masters),
              "opt_states": copy(self.opt_states), "t": self._t}
        if self._efs:
            sd["efs"] = copy(self._efs)
        return sd

    def load_state_dict(self, sd):
        """Restore a `state_dict` snapshot.  Host-numpy leaves (a snapshot
        that round-tripped through a resume capsule's pickled sidecar,
        tpu_mx/resume.py) are accepted and placed back on device —
        deterministic resume depends on this path restoring t, optimizer
        state and weights bit-exactly."""
        def dev(tree):
            return jax.tree_util.tree_map(
                lambda x: jnp.asarray(x) if isinstance(x, np.ndarray)
                else x, tree)

        with self._state_lock:
            self._generation += 1  # invalidate any watchdog-abandoned step
            self.values = dev(sd["values"])
            self.masters = dev(sd.get("masters", {}))
            self.opt_states = dev(sd["opt_states"])
            efs = dev(sd.get("efs") or {})
            if self._efs and efs and all(k in efs and efs[k].shape == v.shape
                                         for k, v in self._efs.items()):
                self._efs = efs  # same dp topology; else keep fresh zeros
            self._t = int(sd["t"])
            self._reset_accumulation()
        if self.mesh is not None:
            self.place()  # host-restored leaves need their mesh shardings

    def _reset_accumulation(self):
        """Discard in-flight microbatch state: restored weights invalidate
        partial gradients accumulated against the previous weights (the
        silent-corruption alternative is worse than dropping ≤K-1
        microbatches).  Caller MUST hold _state_lock — every call site
        does, and tpumx-lint's interprocedural concurrency pass PROVES it
        (lock context propagates through the call graph since ISSUE 10;
        the suppressions that used to sit here are gone because a new
        lock-free caller would be a lint error, not a silent race)."""
        self._micro = 0
        if self._gacc is not None:
            self._gacc = jax.tree_util.tree_map(
                lambda a: jnp.zeros_like(a), self._gacc)

    # -- sharded checkpointing (SURVEY §5.4) ----------------------------------
    def _abstract_state(self):
        """ShapeDtypeStructs of the full train state with CURRENT mesh
        shardings — the restore target, so a checkpoint saved on one mesh
        (e.g. dp=2×tp=2) reshards onto this one (e.g. dp=4) at load."""
        def leaf(spec):
            def f(v):
                sh = sharding_for(self.mesh, spec)
                if sh is None:
                    return jax.ShapeDtypeStruct(jnp.shape(v),
                                                jnp.result_type(v))
                return jax.ShapeDtypeStruct(jnp.shape(v), jnp.result_type(v),
                                            sharding=sh)
            return f

        return {
            "values": {k: leaf(self._specs[k])(v)
                       for k, v in self.values.items()},
            "masters": {k: leaf(self._specs[k])(v)
                        for k, v in self.masters.items()},
            "opt_states": {
                k: jax.tree_util.tree_map(leaf(self._specs[k]),
                                          self.opt_states[k])
                for k in self._diff_keys},
            # efs (compression error feedback) is deliberately NOT part of
            # the checkpoint: it is per-DEVICE residual state whose global
            # shape bakes in the dp size, which would break the
            # reshard-on-restore contract below.  Losing it on restore
            # costs one transient quantization error — acceptable.
            "t": jax.ShapeDtypeStruct((), jnp.int32),
        }

    @property
    def _checkpointer(self):
        """One orbax StandardCheckpointer per step instance — its async
        machinery (background tensorstore commit threads) is reused across
        saves instead of being rebuilt per call."""
        if getattr(self, "_ckpt", None) is None:
            import orbax.checkpoint as ocp
            self._ckpt = ocp.StandardCheckpointer()
        return self._ckpt

    def save_checkpoint(self, path, block=True):
        """Sharded checkpoint: every host writes only its own parameter
        shards, in parallel, via orbax/tensorstore — no gather through host
        memory (the reference gathered to rank 0 and wrote one file;
        REF:python/mxnet/module/module.py save_checkpoint).

        block=False returns as soon as the device→host copy is done (orbax
        async save guarantees source buffers are copied out before save()
        returns), so training continues — and may donate/overwrite the live
        buffers — while tensorstore commits in the background.  Call
        `wait_for_checkpoint()` (or any later save/load, which waits
        internally) before reading the files.

        Durability (docs/robustness.md): after the orbax commit completes, a
        `<path>.commit.json` marker is written atomically NEXT TO the
        checkpoint directory (never inside it — orbax owns that layout).
        The marker is the verified-commit point: a preemption between
        tensorstore's partial writes and the marker leaves a directory that
        `load_checkpoint` treats as suspect, not as the newest state.  For
        async saves the marker lands in `wait_for_checkpoint()`."""
        state = dict(self.state_dict())
        state.pop("efs", None)  # per-device; see _abstract_state
        state["t"] = jnp.asarray(state["t"], jnp.int32)
        ck = self._checkpointer
        if getattr(self, "_pending_commit", None) is not None:
            # an earlier async save is still marker-less: finish and stamp
            # it before its slot is overwritten, or a fully-committed
            # checkpoint would stay permanently unverified
            ck.wait_until_finished()
            self._write_commit_marker()
        ap = os.path.abspath(str(path))
        ck.save(ap, state, force=True)
        self._pending_commit = (ap, int(self._t))  # t of the SAVED state
        if block:
            ck.wait_until_finished()
            self._write_commit_marker()

    @staticmethod
    def commit_marker_path(path):
        return os.path.abspath(str(path)) + ".commit.json"

    def _write_commit_marker(self):
        """Stamp the verified-commit marker for the save that just finished
        (multi-host: every host replace()s the same content onto a shared
        filesystem — idempotent and atomic either way)."""
        import json
        import time
        pending = getattr(self, "_pending_commit", None)
        if pending is None:
            return
        self._pending_commit = None
        p, saved_t = pending
        from ..checkpoint import atomic_write
        with atomic_write(self.commit_marker_path(p), "w") as f:
            f.write(json.dumps({"format": "tpu_mx-orbax-commit-v1",
                                "path": os.path.basename(p),
                                "t": saved_t,
                                "wall_time": time.time()}))

    def wait_for_checkpoint(self):
        """Block until any in-flight async save has committed to disk, then
        stamp its verified-commit marker."""
        if getattr(self, "_ckpt", None) is not None:
            self._ckpt.wait_until_finished()
        self._write_commit_marker()

    def load_checkpoint(self, path, fallback_paths=()):
        """Restore a sharded checkpoint onto THIS step's mesh — the saved
        mesh/layout may differ (dp=2×tp=2 → dp=4 etc.); every host reads
        only the shards its devices need.

        Robustness: a path without its `.commit.json` marker (interrupted
        save) is skipped when `fallback_paths` remain — pass older
        checkpoints newest-first to get elastic-style fall-back.  A
        marker-less path is still *attempted* as legacy (with a warning)
        when it is the last resort; restore errors also advance to the next
        fallback.  Raises MXNetError when no candidate restores."""
        from ..base import MXNetError
        ck = self._checkpointer
        ck.wait_until_finished()  # an async save may still be committing
        self._write_commit_marker()
        logger = logging.getLogger(__name__)
        candidates = [os.path.abspath(str(p))
                      for p in (path, *tuple(fallback_paths))]
        errors = []
        for i, ap in enumerate(candidates):
            last_resort = i == len(candidates) - 1
            if not os.path.exists(ap):
                errors.append(f"{ap}: does not exist")
                continue
            if not os.path.exists(self.commit_marker_path(ap)):
                if not last_resort:
                    logger.warning(
                        "checkpoint %s has no commit marker (interrupted "
                        "or pre-durability save): falling back", ap)
                    errors.append(f"{ap}: no commit marker")
                    continue
                logger.warning(
                    "checkpoint %s has no commit marker: attempting "
                    "unverified restore (legacy/last resort)", ap)
            try:
                state = ck.restore(ap, self._abstract_state())
            except Exception as e:
                logger.warning("checkpoint %s failed to restore (%s: %s)%s",
                               ap, type(e).__name__, e,
                               "" if last_resort else " — falling back")
                errors.append(f"{ap}: {type(e).__name__}: {e}")
                continue
            with self._state_lock:
                self._generation += 1  # invalidate abandoned steps
                self.values = state["values"]
                self.masters = state.get("masters", {})
                self.opt_states = state["opt_states"]
                self._t = int(state["t"])
                self._reset_accumulation()
            return ap
        raise MXNetError("load_checkpoint: no restorable checkpoint among "
                         + "; ".join(errors))


def fsdp_rules(params, axis="dp", min_size=1024, axis_size=None):
    """ZeRO-3/FSDP-style parameter sharding rules (SURVEY §2.3; the
    reference had no analog — its params were replicated per GPU with
    KVStore aggregation).

    Returns [(regex, PartitionSpec)] sharding every parameter whose size
    is >= min_size along its largest axis DIVISIBLE by `axis_size` (pass
    the mesh's dp size; with axis_size=None any largest axis is taken and
    jit will reject non-divisible dims loudly).  Params with no divisible
    axis stay replicated.  Under the compiled step this is textbook
    GSPMD-FSDP: XLA all-gathers each weight just before its matmul and
    reduce-scatters its gradient — per-device parameter+optimizer memory
    drops ~axis-fold, at the cost of those collectives (they overlap with
    compute on ICI)."""
    rules = []
    for name, v in params.items():
        shape = tuple(v.shape)
        if not shape or int(np.prod(shape)) < min_size:
            continue
        dims = sorted(range(len(shape)), key=lambda d: -shape[d])
        dim = None
        for d in dims:  # largest divisible axis; ties -> earliest
            if axis_size is None or shape[d] % axis_size == 0:
                dim = d
                break
        if dim is None:
            continue  # no divisible axis: leave replicated
        spec = [None] * len(shape)
        spec[dim] = axis
        rules.append((f"^{re.escape(name)}$", P(*spec)))
    return rules
