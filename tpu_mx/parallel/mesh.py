"""Device mesh utilities (SURVEY §5.8: the TPU-native replacement for the
reference's KVStore comm topology — ps-lite trees/rings become a
`jax.sharding.Mesh` whose collectives XLA compiles over ICI/DCN).

Axis conventions used across the framework:
  dp   — data parallel (batch sharding; grad psum)
  fsdp — parameter-sharded data parallel (reduce_scatter/all_gather)
  tp   — tensor/model parallel (Megatron-style sharded matmuls)
  sp   — sequence/context parallel (ring attention over ICI)
  pp   — pipeline stages (GPipe microbatching; parallel.pipeline)
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "Mesh", "NamedSharding", "P", "local_mesh",
           "hybrid_mesh"]


def make_mesh(axis_sizes=None, devices=None):
    """Build a Mesh from {axis: size}. Sizes of -1 are inferred to fill the
    device count (at most one -1)."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = {"dp": n}
    names = list(axis_sizes)
    sizes = list(axis_sizes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    known = int(np.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} "
                         f"devices, have {n}")
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, axis_names=tuple(names))


def local_mesh(**axis_sizes):
    return make_mesh(axis_sizes or None)


def hybrid_mesh(ici_axes, dcn_axes=None):
    """Multi-slice (ICI within slice, DCN across): reference's single-machine
    vs cross-machine KVStore split.

    create_hybrid_device_mesh takes per-axis (ici, dcn) factor lists of EQUAL
    length and returns a device array of ndim == len(axes) (elementwise
    product), so both dicts must name the same axes; an axis absent from
    dcn_axes gets dcn factor 1.  hybrid_mesh({'dp': 8, 'tp': 4},
    {'dp': 2}) = 2 slices of 8×4, dp spanning DCN."""
    if not dcn_axes:
        return make_mesh(ici_axes)
    from jax.experimental import mesh_utils
    unknown = set(dcn_axes) - set(ici_axes)
    if unknown:
        raise ValueError(f"dcn axes {sorted(unknown)} not present in ici_axes")
    names = list(ici_axes)
    ici_shape = [ici_axes[n] for n in names]
    dcn_shape = [dcn_axes.get(n, 1) for n in names]
    dev = mesh_utils.create_hybrid_device_mesh(ici_shape, dcn_shape)
    return Mesh(dev, axis_names=tuple(names))
