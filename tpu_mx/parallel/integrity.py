"""Silent-data-corruption defense plane (ISSUE 20).

Every failure the stack survives is *loud* — hangs (supervisor watchdog),
NaNs (numeric sentinel), crashes (durable manifests), torn writes
(checkpoint verify), lost members (fleet leases).  Nothing defended
against a chip that returns plausible-but-wrong numbers: the loss stays
finite, the heartbeat stays fresh, and at fleet scale one corrupt worker
poisons every replica through gradient AllReduce.  This module closes
that gap with three detectors and one verdict type:

1. **Cross-replica state fingerprinting** — after each optimizer update
   every dp replica folds a cheap device-side fingerprint of its
   post-sync parameter tree (:func:`device_fingerprint`, computed INSIDE
   the fused step and read back beside the loss scalar, so the hot-path
   stays one program).  Replicas are bit-identical post-AllReduce by
   construction, so the fingerprints must agree; every K committed steps
   each rank publishes its fingerprint into the fleet membership dir and
   :class:`IntegrityMonitor` compares them.  ANY disagreement is
   corruption — there is no tolerance to tune — and majority vote names
   the minority rank(s).
2. **Sampled shadow-step audit** (:class:`ShadowAuditor`) for the
   no-quorum cases (dp=1, or serving): on a seeded sampled cadence,
   re-execute the identical step — same operands, same compiled program —
   and compare bit-exactly.  The program is deterministic, so a mismatch
   is flaky hardware *by construction*, not a heuristic.
3. **Quarantine** — a corruption verdict raises :class:`DataCorruption`
   (tpu_mx/supervisor.py), a new failure class beside transient/numeric:
   the minority rank writes a permanent quarantine record the fleet
   refuses to re-admit (``Fleet.quarantine``; distinct from lease
   eviction — a healed partition still rejoins, a corrupt chip never
   does), and the surviving majority rolls back to the last *verified*
   checkpoint — the newest save taken at or before the last all-agree
   vote, which the monitor tracks (``verified_step``) and the resume
   capsule carries, so "last known-good" is provable, never guessed.

Fallback ladder when no quorum exists: 3+ replicas → majority vote with
minority attribution; 2 replicas → disagreement is still detected (the
verdict carries an empty minority — both roll back, neither is blamed);
1 replica → the shadow audit is the only witness, and its mismatch
self-attributes.  Serving uses the same auditor as a sampled decode-step
self-check classified into the existing restart ladder.

Everything here is provoked in tests, never assumed: chaos's
``bitflip_grad_rank`` / ``bitflip_param_at_step`` / ``flaky_recompute``
knobs inject the corruption, and the soak CI tier's SDC storm leg gates
the whole detect→attribute→quarantine→recover loop end to end (corrupt
rank quarantined, survivors' final weights bit-equal to an uninjected
run).  See docs/robustness.md "Silent data corruption defense".

The file layout under the fleet root (plain JSON, readable by the
jax-less forensics tools — fleet_obs/fleet_report never import this
module's jax side)::

    <root>/integrity/fp-<rank>.json     newest published fingerprint
    <root>/integrity/votes-<rank>.jsonl this rank's vote verdicts
    <root>/quarantine/<rank>.json       permanent corruption verdicts
"""
from __future__ import annotations

import json
import logging
import os
import time

from .. import checkpoint as _ckpt
from .. import telemetry as _telemetry
from .. import tracing as _tracing
from ..supervisor import DataCorruption

__all__ = ["DataCorruption", "IntegrityMonitor", "ShadowAuditor",
           "device_fingerprint", "host_fingerprint", "bits_equal",
           "sampled"]

log = logging.getLogger(__name__)

#: FNV-1a basis/prime — the fold is FNV-shaped (multiply-and-add over
#: per-leaf bit sums) because it is cheap, order-sensitive across leaves,
#: and a single flipped bit in any leaf always changes the digest: the
#: leaf sum moves by ±2^b (mod 2^32), never 0, and the odd prime
#: multiplier is invertible mod 2^32 so the change survives the fold.
_FNV_BASIS = 2166136261
_FNV_PRIME = 16777619


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------
def _leaf_bits_u32(x):
    """Reinterpret one array's bits as uint32 words (jit-traceable).

    Bitcast, never value-cast: the fingerprint must see the exact bit
    pattern (a flipped mantissa bit that barely moves the value must
    still flip the digest), and NaN payloads must be preserved."""
    import jax.numpy as jnp
    from jax import lax

    dt = jnp.dtype(x.dtype)
    # issubdtype, not dt.kind: ml_dtypes' bfloat16 reports kind "V"
    if jnp.issubdtype(dt, jnp.floating):
        if dt.itemsize == 4:
            return lax.bitcast_convert_type(x, jnp.uint32)
        if dt.itemsize == 2:  # f16 / bf16
            return lax.bitcast_convert_type(x, jnp.uint16) \
                .astype(jnp.uint32)
        if dt.itemsize == 8:
            u64 = lax.bitcast_convert_type(x, jnp.uint64)
            return ((u64 & jnp.uint64(0xFFFFFFFF))
                    ^ (u64 >> jnp.uint64(32))).astype(jnp.uint32)
    if jnp.issubdtype(dt, jnp.integer) or dt == jnp.bool_:
        return x.astype(jnp.uint32)
    raise TypeError(f"device_fingerprint: unsupported leaf dtype {dt}")


def device_fingerprint(tree):
    """Fold a parameter tree into ONE uint32 scalar, on device.

    Jit-traceable — the compiled train step computes it as part of the
    same program that applied the update, so the readback rides the
    existing loss transfer (no extra host↔device round trip, and the
    hot-path-purity lint sees one program).  uint32 arithmetic wraps by
    definition, which is exactly the modular fold we want.  Leaf order
    is ``tree_leaves`` order — deterministic for a fixed tree structure,
    which is all cross-replica comparison needs (every replica runs the
    identical program over the identical structure)."""
    import jax.numpy as jnp
    from jax import tree_util

    acc = jnp.uint32(_FNV_BASIS)
    for leaf in tree_util.tree_leaves(tree):
        if not hasattr(leaf, "dtype"):
            continue
        s = jnp.sum(_leaf_bits_u32(leaf), dtype=jnp.uint32)
        acc = acc * jnp.uint32(_FNV_PRIME) + s
    return acc


def host_fingerprint(value):
    """The host-side twin: fold numpy arrays / scalars / nested
    lists-of-arrays into one Python int with the same FNV shape.  Used
    where the data already lives on host (serving decode tokens, kvstore
    payload checks in tests) — NOT bit-compatible with
    :func:`device_fingerprint` (different leaf flattening), and never
    compared against it."""
    import numpy as np

    acc = _FNV_BASIS
    stack = [value]
    while stack:
        v = stack.pop()
        if v is None:
            continue
        if isinstance(v, (list, tuple)):
            stack.extend(reversed(v))
            continue
        arr = np.asarray(v)
        word = int(np.frombuffer(arr.tobytes(), dtype=np.uint8)
                   .astype(np.uint64).sum() % (1 << 32))
        acc = (acc * _FNV_PRIME + word) % (1 << 32)
    return acc


def bits_equal(a, b):
    """Bit-exact comparison of two step results (ints, numpy arrays, or
    nested lists/tuples of them).  NaN == NaN here — the comparison is
    over bit patterns, not IEEE semantics."""
    import numpy as np

    if isinstance(a, (list, tuple)) or isinstance(b, (list, tuple)):
        if not isinstance(a, (list, tuple)) \
                or not isinstance(b, (list, tuple)) or len(a) != len(b):
            return False
        return all(bits_equal(x, y) for x, y in zip(a, b))
    if a is None or b is None:
        return a is None and b is None
    aa, bb = np.asarray(a), np.asarray(b)
    if aa.shape != bb.shape or aa.dtype != bb.dtype:
        return False
    return aa.tobytes() == bb.tobytes()


# ---------------------------------------------------------------------------
# seeded sampled cadence
# ---------------------------------------------------------------------------
def _mix64(x):
    """splitmix64 finalizer — a stateless seeded hash, so the audit
    schedule is a pure function of (seed, index): deterministic across
    restarts (a resumed run audits the same steps) yet unpredictable
    enough that periodic corruption cannot dodge a periodic audit."""
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


def sampled(seed, index, rate):
    """True when ``index`` is in the seeded sample of density ``rate``."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    h = _mix64((int(seed) << 32) ^ (int(index) & 0xFFFFFFFF))
    return (h / float(1 << 64)) < float(rate)


def _perturb(value):
    """Flip one bit of a step result — the simulated flaky recompute
    (chaos ``flaky_recompute``).  The perturbation lives HERE, next to
    the comparison it must defeat, so the chaos module stays
    numerics-free."""
    import numpy as np

    if isinstance(value, (list, tuple)):
        return type(value)([_perturb(value[0])] + list(value[1:]))
    if value is None:
        return value
    arr = np.asarray(value)
    if arr.size == 0:
        return value
    flat = arr.copy().reshape(-1).view(np.uint8)
    flat[0] ^= 1
    out = flat.view(arr.dtype).reshape(arr.shape)
    return int(out) if np.isscalar(value) or arr.shape == () else out


def _record_fp_at(rec, step):
    """The fingerprint a published record carries for ``step`` — the
    newest entry or one from its history ring — or None."""
    if not isinstance(rec, dict):
        return None
    if int(rec.get("step", -1)) == int(step):
        return int(rec["fp"])
    for s, v in rec.get("history") or ():
        if int(s) == int(step):
            return int(v)
    return None


# ---------------------------------------------------------------------------
# cross-replica fingerprint voting
# ---------------------------------------------------------------------------
class IntegrityMonitor:
    """One rank's handle on the fleet's fingerprint-vote protocol.

    ``root`` is the fleet membership dir (or any shared dir for tests);
    ``rank`` this replica's slot; ``world`` the ranks expected to vote
    (refresh with :meth:`set_world` after a reshard).  ``interval`` is K:
    fingerprints are published and compared every K committed steps —
    detection latency is bounded by K, which is the knob trading audit
    I/O against blast radius.  ``fingerprint_fn`` is a zero-arg callable
    returning the step's digest (``CompiledTrainStep.fingerprint``);
    the supervisor calls :meth:`on_committed_step` at every step
    boundary, which raises :class:`DataCorruption` on a disagreeing
    vote.

    The monitor is deliberately fleet-*agnostic* (plain dir paths, no
    Fleet import): the forensics side (fleet_obs/fleet_report) reads the
    same files without jax, and tests drive multi-rank votes from one
    process."""

    def __init__(self, root, rank=0, world=None, interval=8,
                 fingerprint_fn=None, history=256, vote_timeout=2.0,
                 poll=0.02, heartbeat=None):
        self.root = os.fspath(root)
        self.rank = int(rank)
        self.world = sorted(int(m) for m in (world or [rank]))
        self.interval = max(1, int(interval))
        self.fingerprint_fn = fingerprint_fn
        self.history_limit = int(history)
        self.vote_timeout = float(vote_timeout)
        self.poll = float(poll)
        # called every poll iteration of a vote wait: a rank blocked on
        # slower peers must keep renewing its fleet lease, or the wait
        # itself reads as a partition (pass Fleet.heartbeat)
        self.heartbeat = heartbeat
        self.history = []              # [(step, fp), ...] ring
        self._pub_ring = []            # published (step, fp) pairs
        self.verified_step = 0         # last all-agree vote step
        self.first_disagree_step = None
        self.published = 0
        os.makedirs(self._dir(), exist_ok=True)

    # -- files ------------------------------------------------------------
    def _dir(self):
        return os.path.join(self.root, "integrity")

    def _fp_path(self, rank):
        return os.path.join(self._dir(), f"fp-{int(rank)}.json")

    def _votes_path(self):
        return os.path.join(self._dir(), f"votes-{self.rank}.jsonl")

    def set_world(self, world):
        """Adopt a new voting cohort (after a reshard/quarantine — the
        vote must not wait on a rank that is no longer in the world)."""
        self.world = sorted(int(m) for m in world)

    # -- publish / read ---------------------------------------------------
    def publish(self, step, fp):
        """Atomically publish this rank's fingerprint for ``step``.

        The record carries a short ring of PRIOR published (step, fp)
        pairs: a fast rank overwrites this file long before slow peers
        reach their vote for an earlier step, and without the ring those
        voters would be starved of the very record they compare (30s
        timeout stalls, missed attribution — the fp file is newest-only
        by design, the ring is what makes the vote race-free)."""
        self._pub_ring.append((int(step), int(fp)))
        del self._pub_ring[:-32]
        body = {"member": self.rank, "step": int(step), "fp": int(fp),
                "wall_time": time.time(),
                "history": [[s, v] for s, v in self._pub_ring]}
        with _ckpt.atomic_write(self._fp_path(self.rank), mode="w") as f:
            f.write(json.dumps(body))
        self.published += 1
        _telemetry.counter("integrity.fingerprints").inc()
        _tracing.emit("integrity.fingerprint", step=int(step), fp=int(fp),
                      rank=self.rank)

    def peers(self):
        """All published fingerprint records: {rank: record}."""
        out = {}
        try:
            names = os.listdir(self._dir())
        except OSError:
            return out
        for name in names:
            if not (name.startswith("fp-") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self._dir(), name),
                          encoding="utf-8") as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            if isinstance(rec, dict) and "member" in rec:
                out[int(rec["member"])] = rec
        return out

    # -- the vote ---------------------------------------------------------
    def vote(self, step, wait=True):
        """Compare the cohort's fingerprints at ``step``.

        Waits up to ``vote_timeout`` for every world rank to publish the
        step's record (all ranks publish on the same committed-step
        cadence, so the wait covers scheduling skew, not drift).  Ranks
        that never show are counted absent — the vote proceeds among
        those present when at least two did (below that there is nothing
        to compare: shadow audits are the dp=1 story).  Returns the
        verdict dict (also appended to this rank's ``votes-*.jsonl``),
        or None when no quorum formed."""
        deadline = time.monotonic() + (self.vote_timeout if wait else 0.0)
        next_beat = 0.0
        while True:
            recs = self.peers()
            votes = {}
            for m in self.world:
                fp = _record_fp_at(recs.get(m), step)
                if fp is not None:
                    votes[m] = fp
            if len(votes) == len(self.world) \
                    or time.monotonic() >= deadline:
                break
            if self.heartbeat is not None \
                    and time.monotonic() >= next_beat:
                next_beat = time.monotonic() + 0.25
                try:
                    self.heartbeat()
                except Exception:   # noqa: BLE001 — lease renewal is
                    pass            # best-effort inside the wait
            time.sleep(self.poll)
        if len(votes) < 2:
            return None
        counts = {}
        for fp in votes.values():
            counts[fp] = counts.get(fp, 0) + 1
        majority_fp, majority_n = max(counts.items(),
                                      key=lambda kv: (kv[1], -kv[0]))
        agree = len(counts) == 1
        # a strict majority names the minority; a tie (2 ranks, or 2v2)
        # detects corruption but cannot attribute — minority stays empty
        # and every voter treats itself as a survivor (rolls back)
        quorum = majority_n * 2 > len(votes)
        minority = sorted(m for m, fp in votes.items()
                          if fp != majority_fp) if quorum and not agree \
            else []
        verdict = {"step": int(step), "agree": bool(agree),
                   "quorum": bool(quorum), "majority_fp": int(majority_fp),
                   "votes": {str(m): int(fp) for m, fp in votes.items()},
                   "minority": [int(m) for m in minority],
                   "absent": sorted(m for m in self.world
                                    if m not in votes),
                   "world": list(self.world), "wall_time": time.time()}
        self._record_vote(verdict)
        _telemetry.counter("integrity.votes").inc()
        _tracing.emit("integrity.vote", step=int(step), agree=bool(agree),
                      majority_fp=int(majority_fp),
                      minority=",".join(str(m) for m in minority),
                      world_size=len(votes))
        if agree:
            # certification needs the FULL cohort: an agree vote among a
            # subset (a peer's publish raced the timeout) proves nothing
            # about the absent ranks, so it must not advance the
            # rollback anchor
            if not verdict["absent"]:
                self.verified_step = max(self.verified_step, int(step))
                _telemetry.gauge("integrity.verified_step") \
                    .set(self.verified_step)
        else:
            _telemetry.counter("integrity.mismatches").inc()
            if self.first_disagree_step is None \
                    or int(step) < self.first_disagree_step:
                self.first_disagree_step = int(step)
        return verdict

    def _record_vote(self, verdict):
        try:
            with open(self._votes_path(), "a", encoding="utf-8") as f:
                f.write(json.dumps(verdict) + "\n")
        except OSError:
            pass  # forensics must never fail the step they describe

    # -- the supervised-step hook -----------------------------------------
    def on_committed_step(self, step, fp=None):
        """The per-step duty cycle, called by the supervisor after each
        committed step: fold the fingerprint into history and, every
        ``interval`` steps, publish + vote.  Raises
        :class:`DataCorruption` when the vote disagrees — at the step
        boundary, the same quiesce point membership changes use, so the
        rollback never lands mid-collective."""
        if fp is None and self.fingerprint_fn is not None:
            fp = self.fingerprint_fn()
        if fp is None:
            return None
        step, fp = int(step), int(fp)
        self.history.append((step, fp))
        if len(self.history) > self.history_limit:
            del self.history[:len(self.history) - self.history_limit]
        if step % self.interval != 0:
            return None
        self.publish(step, fp)
        verdict = self.vote(step)
        if verdict is None or verdict["agree"]:
            return verdict
        minority = verdict["minority"]
        self_corrupt = self.rank in minority
        raise DataCorruption(
            f"cross-replica fingerprint vote disagreed at step {step}: "
            f"rank {self.rank} fp={fp:#010x}, majority "
            f"fp={verdict['majority_fp']:#010x}, minority "
            f"{minority or '(no quorum to attribute)'} — "
            + ("this rank is corrupt: quarantine" if self_corrupt else
               "rolling back to the last verified checkpoint "
               f"(step {self.verified_step})"),
            step=step, minority=minority,
            verified_step=self.verified_step, surface="train",
            self_corrupt=self_corrupt)

    # -- capsule seam ------------------------------------------------------
    def state_dict(self):
        """The fingerprint ledger the resume capsule carries — a restored
        run knows its last PROVEN-good step (and any disagreement it was
        recovering from) instead of re-deriving trust from nothing."""
        return {"rank": self.rank, "interval": self.interval,
                "history": [[int(s), int(f)] for s, f in self.history],
                "verified_step": int(self.verified_step),
                "first_disagree_step": self.first_disagree_step,
                "published": int(self.published)}

    def load_state_dict(self, state):
        self.history = [(int(s), int(f))
                        for s, f in state.get("history", [])]
        self.verified_step = int(state.get("verified_step", 0))
        fd = state.get("first_disagree_step")
        self.first_disagree_step = None if fd is None else int(fd)
        self.published = int(state.get("published", 0))


# ---------------------------------------------------------------------------
# shadow-step audits (the no-quorum detector)
# ---------------------------------------------------------------------------
class ShadowAuditor:
    """Sampled bit-exact re-execution — corruption detection when there
    is no peer to vote with (dp=1 training, or a serving engine).

    ``rate`` is the sampled audit density (0 disarms), ``seed`` fixes
    the schedule (:func:`sampled` — deterministic across restarts).
    :meth:`should_audit` asks whether this step is in the sample;
    :meth:`audit` runs the comparison: ``first`` is the committed
    result (fingerprint int, token array, or nested tuple — anything
    :func:`bits_equal` takes), ``recompute`` a zero-arg callable
    re-executing the IDENTICAL program on the identical operands.  The
    program is deterministic, so first != recompute is flaky hardware by
    construction — :class:`DataCorruption`, self-attributed
    (``self_corrupt=True``: there is no one else to blame).  The chaos
    ``flaky_recompute`` knob perturbs the recomputed value here, so the
    false-positive arm of the detector is testable."""

    def __init__(self, rate=0.0, seed=0, surface="train"):
        self.rate = float(rate)
        self.seed = int(seed)
        self.surface = str(surface)
        self.audits = 0
        self.mismatches = 0

    def should_audit(self, index):
        return sampled(self.seed, index, self.rate)

    def audit(self, first, recompute, step=0):
        """Compare the committed result against a shadow re-execution;
        returns True on a bit-exact match, raises otherwise."""
        from ..contrib import chaos

        self.audits += 1
        _telemetry.counter("integrity.shadow_audits").inc()
        second = recompute()
        if chaos.maybe_flaky_recompute():
            second = _perturb(second)
        ok = bits_equal(first, second)
        _tracing.emit("integrity.shadow_audit", step=int(step),
                      match=bool(ok), surface=self.surface)
        if ok:
            return True
        self.mismatches += 1
        _telemetry.counter("integrity.shadow_mismatches").inc()
        raise DataCorruption(
            f"shadow-step audit mismatch at {self.surface} step {step}: "
            "re-executing the identical program on the identical operands "
            "produced different bits — flaky hardware on this worker",
            step=int(step), surface=self.surface, self_corrupt=True)

    def maybe_audit(self, index, first, recompute):
        """``audit`` iff ``index`` is in the seeded sample (the one-call
        form the serving self-check uses)."""
        if not self.should_audit(index):
            return None
        return self.audit(first, recompute, step=index)
