"""tpu_mx.parallel — mesh/SPMD layer (the reference's KVStore+launcher tier
re-designed for ICI/DCN collectives; SURVEY §2.3, §5.7, §5.8)."""
from .fleet import Fleet, MembershipChange, reshard_live
from .mesh import Mesh, NamedSharding, P, hybrid_mesh, local_mesh, make_mesh
from .moe import MoEFFN, moe_sharding_rules
from .pipeline import pipeline_apply, stack_stage_params
from .ring_attention import attention, local_flash_attention, ring_attention
from .ulysses import get_sp_strategy, set_sp_strategy, ulysses_attention
from .train_step import (CompiledTrainStep, apply_rules, fsdp_rules,
                         sharding_for)
