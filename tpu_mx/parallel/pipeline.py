"""Pipeline parallelism: GPipe-style microbatch pipelining over a `pp` mesh
axis (SURVEY §2.3's last parallelism row; the reference had no pipeline
support — MXNet model-parallel was manual ctx placement per layer,
REF:example/model-parallel).

TPU-native design: all `pp` stages run the SAME program under `shard_map`
(SPMD, like everything else on the mesh) instead of the reference-era
one-process-per-stage scheme.  Stage parameters are stacked along a leading
stage axis sharded over `pp`, activations rotate stage→stage+1 with
`lax.ppermute`, and a `lax.scan` over M + S - 1 ticks drives the classic
GPipe schedule (stage s computes microbatch t−s at tick t; the first/last
S−1 ticks are the pipeline bubble).  Gradients flow through the transpose
of the same scan/ppermute program — no separate backward schedule to write.

Composes with `dp` (microbatch batch axis sharded over dp) and the other
mesh axes: specs are PartitionSpecs on the same mesh the rest of the train
step uses.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_apply", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """[params_pytree per stage] -> one pytree with a leading stage axis
    (the layout pipeline_apply shards over `pp`).  All stages must share a
    structure and per-leaf shape (uniform stages, the GPipe contract)."""
    return jax.tree_util.tree_map(lambda *leaves: jnp.stack(leaves),
                                  *per_stage_params)


def pipeline_apply(stage_fn, stacked_params, x, mesh, axis_name="pp",
                   num_microbatches=None, data_spec=None):
    """Run `x` through S pipeline stages of `stage_fn`, microbatched.

    stage_fn(params, x_mb) -> y_mb — one stage's computation; activations
        must keep the same shape/dtype across stages (uniform stages).
    stacked_params — pytree whose leaves have a leading stage axis of size
        S == mesh.shape[axis_name] (see stack_stage_params).
    x — (B, ...) global batch; B must divide into `num_microbatches`
        (default S) microbatches.
    data_spec — PartitionSpec for one microbatch's dims starting at the
        batch axis, e.g. P('dp') to shard each microbatch's batch over dp
        (default: replicated).

    Returns (B, ...) outputs replicated over `axis_name` (broadcast from
    the last stage), sharded per `data_spec` elsewhere.
    """
    S = mesh.shape[axis_name]
    M = num_microbatches or S
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    from jax.experimental.shard_map import shard_map

    xs = x.reshape((M, B // M) + x.shape[1:])
    dspec = tuple(data_spec) if data_spec is not None else ()
    x_spec = P(*((None,) + dspec))               # (M, mb, ...): pp-replicated
    p_spec = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)

    body = functools.partial(_pipeline_body, stage_fn=stage_fn,
                             axis_name=axis_name, n_stages=S, n_micro=M)
    fn = shard_map(body, mesh=mesh, in_specs=(p_spec, x_spec),
                   out_specs=x_spec, check_rep=False)
    out = fn(stacked_params, xs)
    return out.reshape((B,) + out.shape[2:])


def _pipeline_body(params_local, xs, *, stage_fn, axis_name, n_stages,
                   n_micro):
    """Inside shard_map: params_local leaves are (1, ...) — this stage's
    slice; xs is (M, mb_local, ...) with every microbatch present."""
    p = jax.tree_util.tree_map(lambda a: a[0], params_local)
    s_idx = lax.axis_index(axis_name)
    S, M = n_stages, n_micro
    perm = [(i, (i + 1) % S) for i in range(S)]
    state = jnp.zeros(xs.shape[1:], xs.dtype)    # activation arriving here
    out = jnp.zeros_like(xs)                     # filled on the last stage

    def tick(carry, t):
        state, out = carry
        # stage 0 feeds itself from the input queue; later stages consume
        # what the previous stage permuted over last tick
        x_t = lax.dynamic_index_in_dim(xs, jnp.clip(t, 0, M - 1), 0,
                                       keepdims=False)
        inp = jnp.where(s_idx == 0, x_t, state)
        y = stage_fn(p, inp)
        # the microbatch completing at the last stage this tick
        m_out = t - (S - 1)
        idx = jnp.clip(m_out, 0, M - 1)
        cur = lax.dynamic_index_in_dim(out, idx, 0, keepdims=False)
        valid = (s_idx == S - 1) & (m_out >= 0)
        out = lax.dynamic_update_index_in_dim(
            out, jnp.where(valid, y, cur), idx, 0)
        state = lax.ppermute(y, axis_name, perm)
        return (state, out), None

    (state, out), _ = lax.scan(tick, (state, out), jnp.arange(M + S - 1))
    # broadcast the last stage's buffer to every pp rank (others hold zeros)
    return lax.psum(jnp.where(s_idx == S - 1, out, jnp.zeros_like(out)),
                    axis_name)
