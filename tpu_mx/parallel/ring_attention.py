"""Ring attention: sequence/context parallelism over the ICI ring
(SURVEY §5.7 — a NEW capability, absent in the reference, whose max sequence
length was bounded by one device's memory).

Design: the sequence axis is sharded over mesh axis `sp`.  Each device holds a
(T/n)-length Q block and streams K/V blocks around the ring with
`lax.ppermute`, accumulating flash-attention style online-softmax statistics
(running max m, denominator l, numerator o) so the full T×T attention is
computed in n steps with O(T/n) memory per device and compute/communication
overlap on ICI.  Causal masking uses the rotating K-block index, and
key-padding masks (`valid_length`, the reference-era GluonNLP BERT contract)
ride the same index: each rotating K block masks its own global positions.

The same blockwise kernel with n=1 is the local attention path, so models can
call `attention()` unconditionally and get ring behavior exactly when the
mesh has an `sp` axis.

Attention-prob dropout: on the ring and dense paths the keep-mask is drawn
per (device, ring-step) from a folded key; on the local TPU path it runs
inside the Pallas kernel's PRNG (kernels.flash_attention).  The softmax
normalizer always uses the un-dropped probabilities.
"""
from __future__ import annotations

import functools
import logging
import math
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ring_attention", "attention", "local_flash_attention",
           "dispatch_counts"]

_logger = logging.getLogger(__name__)

# Which attention path each distinct call signature took.  Deduplicated by
# (path, detail): under jit this is once per compilation; on the eager path
# it is once per new shape/dtype — so a shape regression that silently drops
# the Pallas kernel shows up exactly once, not once per step (VERDICT r1
# weak#6).  Mirrored into profiler counters.
dispatch_counts = {"ring": 0, "ulysses": 0, "pallas_flash": 0,
                   "xla_dense": 0}


def _dense_max_kv():
    """Largest kv_len at which 'auto' prefers XLA dense attention over the
    Pallas flash kernel.  r4 on-chip A/B (fwd+bwd, causal, bf16, H=12,
    D=64, constant token count): dense wins 34% at T=128, 25% at 256, 5%
    at 512; flash wins 18% at 1024 and 31% at 2048 — the kernel's
    grid/DMA overhead amortizes only once many 128-blocks are in flight.
    The default stays at 512 rather than the ~768-1024 perf crossover
    because dense materializes O(B·H·T²) probabilities in the backward,
    and that memory cliff arrives before the perf one.  Read per call
    (like TPUMX_ATTENTION) so probes can sweep the crossover at
    runtime."""
    return int(os.environ.get("TPUMX_DENSE_MAX_KV", "512"))


_seen_signatures = set()


def _count(path, detail="", warn=False):
    sig = (path, detail)
    if sig in _seen_signatures:
        return
    _seen_signatures.add(sig)
    dispatch_counts[path] += 1
    try:
        from .. import profiler
        profiler.Counter(f"attention_dispatch_{path}",
                         domain="tpu_mx").increment()
    except Exception:
        pass
    if warn:
        # dense fallback on a TPU backend is a perf bug worth shouting about
        _logger.warning("attention: dense O(T^2) XLA fallback (%s)", detail)
    else:
        _logger.info("attention dispatch: %s %s", path, detail)


def _block_attn(q, k, v, bias=None, mask=None, scale=1.0,
                dropout_rate=0.0, dropout_key=None):
    """One q-block × k-block attention: returns (scores-exp sum stats).
    q: (B, H, Tq, D), k/v: (B, H, Tk, D).  mask: bool, True = attend.
    Dropout hits only the V-accumulation; the denominator l stays
    un-dropped (standard inverted dropout on softmax probs)."""
    # scores and softmax statistics in f32 regardless of input dtype
    # (bf16 exp/max over T keys loses ~3 decimal digits; the MXU
    # accumulates f32 internally anyway, preferred_element_type just
    # keeps it).  Callers cast the normalized output back to q.dtype.
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)                                   # (B,H,Tq)
    # guard fully-masked rows: exp(-inf - -inf) -> use max(m, finite floor)
    m_safe = jnp.maximum(m, -1e30)
    p = jnp.exp(s - m_safe[..., None])                        # (B,H,Tq,Tk)
    l = jnp.sum(p, axis=-1)                                   # (B,H,Tq)
    if dropout_rate > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    # probs cast to v.dtype for the AV matmul (flash-kernel numerics: the
    # softmax stats m/l stay f32, only the normalized weights round).  On
    # the dense path p is a materialized (B,H,Tq,Tk) HBM tensor and the
    # default MXU precision truncates f32 dot operands to bf16 anyway —
    # keeping p f32 paid double the HBM bytes for no extra matmul
    # precision; f32 accumulation is preserved via preferred_element_type.
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)        # (B,H,Tq,D)
    return m_safe, l, o


def _merge(m1, l1, o1, m2, l2, o2):
    """Merge two online-softmax partial results."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = l1 * a1 + l2 * a2
    o = o1 * a1[..., None] + o2 * a2[..., None]
    return m, l, o


def _ring_chunk(tb, prefer=1024):
    """Static inner-chunk size for one ring step: the per-step score block
    is (Tb, C), NOT (Tb, Tb) — this is what keeps device memory O(T/n·C)
    at long context instead of O((T/n)²).  `prefer` is overridable per
    call (ring_attention(step_chunk=...)); any value that doesn't divide
    Tb falls down the power-of-two ladder."""
    if tb <= prefer:
        return tb
    if tb % prefer == 0:
        return prefer
    for c in (512, 256, 128):
        if c <= prefer and tb % c == 0:
            return c
    return tb


def _ring_body(q, k, v, valid, seed, bias, *, axis_name, causal, scale,
               rate, masked, dropped, biased, key_axes=(),
               step_chunk=None):
    """Runs inside shard_map: q/k/v are LOCAL blocks (B, H, Tb, D);
    valid (B,) global key counts (replicated over the ring) or a dummy;
    seed (1,) int32 or a dummy — staticness comes from masked/dropped;
    bias is this device's (B|1, H|1, Tb, T_global) row-slice of the
    attention bias (ALiBi, relative position, …): each ring step slices
    the columns belonging to the K block it currently holds.
    key_axes: every mesh axis the q spec shards over — each device's
    dropout key folds in ALL its coordinates, so shards that differ only
    in dp/tp draw independent masks (not the same mask on different data)."""
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, H, Tb, D = q.shape
    # f32 carries: _block_attn emits f32 stats/partials (see its score
    # comment); the final normalize casts back to q.dtype
    neg = jnp.full((B, H, Tb), -1e30, jnp.float32)
    zero_l = jnp.zeros((B, H, Tb), jnp.float32)
    zero_o = jnp.zeros(q.shape, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    base_key = None
    if dropped:
        # tpumx-lint: disable=determinism -- key is a pure function of the
        # caller-provided seed input (traced), not a hidden fresh stream
        base_key = jax.random.PRNGKey(seed[0])
        for ax in key_axes:
            base_key = jax.random.fold_in(base_key, lax.axis_index(ax))

    C = _ring_chunk(Tb, step_chunk) if step_chunk else _ring_chunk(Tb)
    nchunks = Tb // C
    qpos = my_idx * Tb + jnp.arange(Tb)

    def _sub_attn(m, l, o, k_idx, i, ci, k_sub, v_sub):
        """One (Tb, C) sub-block of the current ring step: masks/bias/
        dropout keys all derive from the GLOBAL key position of the
        chunk, so chunking changes memory, not math (dropout draws are
        keyed per (step, chunk) instead of per step — an equally valid
        stream, noted in the docstring)."""
        kpos = k_idx * Tb + ci * C + jnp.arange(C)
        mask = None
        if causal:
            mask = (qpos[:, None] >= kpos[None, :])[None, None]
        if masked:
            km = kpos[None, None, None, :] < valid[:, None, None, None]
            mask = km if mask is None else jnp.logical_and(mask, km)
        b_blk = None
        if biased:
            b_blk = lax.dynamic_slice_in_dim(bias, k_idx * Tb + ci * C, C,
                                             axis=3)
        key_i = (jax.random.fold_in(base_key, i * nchunks + ci)
                 if dropped else None)
        bm, bl, bo = _block_attn(q, k_sub, v_sub, bias=b_blk, mask=mask,
                                 scale=scale,
                                 dropout_rate=rate if dropped else 0.0,
                                 dropout_key=key_i)
        return _merge(m, l, o, bm, bl, bo)

    def step(carry, i):
        m, l, o, k_cur, v_cur = carry
        k_idx = (my_idx - i) % n  # whose K block we currently hold
        if nchunks == 1:
            m, l, o = _sub_attn(m, l, o, k_idx, i, 0, k_cur, v_cur)
        else:
            def kchunk(c2, ci):
                m2, l2, o2 = c2
                k_sub = lax.dynamic_slice_in_dim(k_cur, ci * C, C, axis=2)
                v_sub = lax.dynamic_slice_in_dim(v_cur, ci * C, C, axis=2)
                return _sub_attn(m2, l2, o2, k_idx, i, ci, k_sub,
                                 v_sub), None

            (m, l, o), _ = lax.scan(kchunk, (m, l, o),
                                    jnp.arange(nchunks))
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m, l, o, k_nxt, v_nxt), None

    (m, l, o, _, _), _ = lax.scan(
        step, (neg, zero_l, zero_o, k, v), jnp.arange(n))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name="sp", causal=False,
                   q_spec=None, valid_length=None, dropout_rate=0.0,
                   dropout_key=None, bias=None, batch_axes=("dp", "tp"),
                   step_chunk=None):
    """Sequence-parallel attention.  q/k/v: GLOBAL (B, H, T, D) arrays whose
    T axis is sharded over `axis_name`.  Returns attention output with the
    same sharding.  `q_spec` overrides the default
    P(batch_axes[0], batch_axes[1], axis_name, None) layout (axes absent
    from the mesh are dropped automatically; pass `batch_axes` to rename
    the batch/heads mesh axes without a full spec).
    valid_length: (B,) int32 valid key counts (global positions).
    dropout_rate/dropout_key: attention-prob dropout, drawn per ring step.
    bias: (B|1, H|1, T, T) additive attention bias (ALiBi, relative
    position, …) — rows shard with q over `axis_name`, columns stay whole
    and are sliced per ring step to match the rotating K block."""
    from jax.experimental.shard_map import shard_map

    def present(ax):
        return ax in mesh.axis_names

    bax, hax = (tuple(batch_axes) + (None, None))[:2]
    spec = q_spec or P(bax if bax and present(bax) else None,
                       hax if hax and present(hax) else None,
                       axis_name if present(axis_name) else None,
                       None)
    scale = 1.0 / math.sqrt(q.shape[-1])
    dropped = dropout_rate > 0.0 and dropout_key is not None
    if not present(axis_name):
        # no sequence axis: plain (flash-style blockwise on one device)
        mask = _dense_mask(q.shape[2], k.shape[2], causal, valid_length)
        m, l, o = _block_attn(q, k, v, bias=bias, mask=mask, scale=scale,
                              dropout_rate=dropout_rate if dropped else 0.0,
                              dropout_key=dropout_key)
        return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)

    _count("ring", f"sp={mesh.shape[axis_name]} shape={q.shape}")
    masked = valid_length is not None
    biased = bias is not None
    valid, seed, vspec = _sp_valid_seed(q, masked, dropped, valid_length,
                                        dropout_key, spec)
    bias_arr = bias if biased else jnp.zeros((1, 1, q.shape[2], 1), q.dtype)
    # valid is per-batch → shard like q's batch axis; seed replicated;
    # bias rows follow the q sharding (batch/head axes only when the bias
    # actually carries them), columns replicated
    bspec = P(spec[0] if biased and bias_arr.shape[0] > 1 else None,
              spec[1] if biased and bias_arr.shape[1] > 1 else None,
              spec[2], None)
    key_axes = tuple(ax for ax in spec if ax is not None)
    fn = shard_map(
        functools.partial(_ring_body, axis_name=axis_name, causal=causal,
                          scale=scale, rate=float(dropout_rate),
                          masked=masked, dropped=dropped, biased=biased,
                          key_axes=key_axes, step_chunk=step_chunk),
        mesh=mesh, in_specs=(spec, spec, spec, vspec, P(None), bspec),
        out_specs=spec, check_rep=False)
    return fn(q, k, v, valid, seed, bias_arr)


def _sp_valid_seed(q, masked, dropped, valid_length, dropout_key, spec):
    """Shared shard_map prologue for the sp strategies (ring, ulysses):
    the (B,) valid-key counts, the scalar dropout seed, and the valid
    spec.  Dummies keep the jitted signature static when a feature is
    off."""
    B = q.shape[0]
    valid = (jnp.asarray(valid_length, jnp.int32) if masked
             else jnp.zeros((B,), jnp.int32))
    seed = (jax.random.randint(dropout_key, (1,), 0, 2 ** 31 - 1, jnp.int32)
            if dropped else jnp.zeros((1,), jnp.int32))
    vspec = P(spec[0]) if masked else P(None)
    return valid, seed, vspec


def _dense_mask(t, tk, causal, valid_length):
    """Combined causal + key-padding mask, or None.  True = attend."""
    mask = None
    if causal:
        mask = (jnp.arange(t)[:, None] >= jnp.arange(tk)[None, :])[None, None]
    if valid_length is not None:
        km = (jnp.arange(tk)[None, None, None, :] <
              jnp.asarray(valid_length, jnp.int32)[:, None, None, None])
        mask = km if mask is None else jnp.logical_and(mask, km)
    return mask


def local_flash_attention(q, k, v, causal=False, valid_length=None,
                          dropout_rate=0.0, dropout_key=None, bias=None):
    """Single-device attention with the same numerics as the ring kernel.
    On TPU with tile-friendly shapes this runs the Pallas flash kernel
    (tpu_mx.kernels.flash_attention: blockwise online softmax, O(T) memory,
    in-kernel padding mask, prob dropout, and additive bias — ALiBi/
    relative-position tensors stream block-by-block with a differentiable
    d_bias); otherwise the XLA dense path."""
    from ..kernels import flash_attention as fa
    on_tpu = jax.default_backend() == "tpu"
    dropped = dropout_rate > 0.0 and dropout_key is not None
    rate = float(dropout_rate) if dropped else 0.0
    # TPUMX_ATTENTION=dense|flash|auto (default auto): at short T the
    # O(T²) score matrix is a single MXU tile and XLA's fused dense
    # attention beats the Pallas kernel's grid/DMA overhead — measured on
    # the r4 chip at T=128, BERT-base batch 512: dense 577 seq/s vs flash
    # 454 (MFU_PROBE_r04.json).  'auto' therefore picks dense up to
    # TPUMX_DENSE_MAX_KV (default 512 — see _dense_max_kv for the full
    # crossover table) and flash beyond; 'flash'/'dense' pin the path
    # ('flash' only where supported() holds; 'dense' always works).
    mode = os.environ.get("TPUMX_ATTENTION", "auto")
    if mode not in ("auto", "dense", "flash"):
        raise ValueError(f"TPUMX_ATTENTION must be auto|dense|flash, "
                         f"got {mode!r}")
    want_flash = on_tpu and mode != "dense" and \
        not (mode == "auto" and k.shape[2] <= _dense_max_kv())
    if want_flash and fa.supported(q.shape, q.dtype, kv_len=k.shape[2],
                                   dropout_rate=rate):
        _count("pallas_flash", f"shape={q.shape}")
        seed = (jax.random.randint(dropout_key, (1,), 0, 2 ** 31 - 1,
                                   jnp.int32) if dropped else None)
        return fa.mha_flash_attention(q, k, v, causal=causal,
                                      valid_length=valid_length,
                                      dropout_rate=rate, dropout_seed=seed,
                                      bias=bias)
    # CPU dense is expected, and a DELIBERATE dense choice (the A/B pin,
    # or auto's measured short-T preference) must not fire the
    # perf-regression warning — it exists for wanted-but-unsupported flash
    _count("xla_dense",
           f"shape={q.shape} dtype={q.dtype} kv_len={k.shape[2]}",
           warn=want_flash)
    scale = 1.0 / math.sqrt(q.shape[-1])
    mask = _dense_mask(q.shape[2], k.shape[2], causal, valid_length)
    m, l, o = _block_attn(q, k, v, bias=bias, mask=mask, scale=scale,
                          dropout_rate=rate, dropout_key=dropout_key)
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def attention(q, k, v, mesh=None, causal=False, valid_length=None,
              dropout_rate=0.0, dropout_key=None, bias=None,
              sp_strategy=None):
    """Dispatch: sequence-parallel attention when a mesh with an `sp` axis
    is active (strategy 'ring' or 'ulysses' — per-call `sp_strategy`, else
    the module default set via `parallel.set_sp_strategy`; ulysses needs
    H % sp == 0 and quietly falls back to ring otherwise), local flash
    when not.  valid_length (B,) masks padded keys; dropout is
    attention-prob dropout (pass a key only in training mode); bias is an
    additive (B|1, H|1, Tq, Tk) attention bias (ALiBi, relative pos)."""
    if sp_strategy is not None and sp_strategy not in ("ring", "ulysses"):
        # validate on EVERY call, not just sp>1 meshes — a typo must not
        # silently select the local path
        raise ValueError(
            f"unknown sp_strategy {sp_strategy!r}; use 'ring' or "
            "'ulysses'")
    if mesh is not None and "sp" in mesh.axis_names and \
            mesh.shape["sp"] > 1:
        from .ulysses import get_sp_strategy, ulysses_attention
        strategy = sp_strategy or get_sp_strategy()
        # ulysses preconditions: heads divide sp, and no REAL head-axis
        # sharding (size-1 tp is fine) — otherwise quiet ring fallback
        if strategy == "ulysses" and q.shape[1] % mesh.shape["sp"] == 0 \
                and mesh.shape.get("tp", 1) == 1:
            return ulysses_attention(q, k, v, mesh, causal=causal,
                                     valid_length=valid_length,
                                     dropout_rate=dropout_rate,
                                     dropout_key=dropout_key, bias=bias)
        return ring_attention(q, k, v, mesh, causal=causal,
                              valid_length=valid_length,
                              dropout_rate=dropout_rate,
                              dropout_key=dropout_key, bias=bias)
    return local_flash_attention(q, k, v, causal=causal,
                                 valid_length=valid_length,
                                 dropout_rate=dropout_rate,
                                 dropout_key=dropout_key, bias=bias)
