"""Mixture-of-Experts FFN with expert parallelism (above-parity: the
reference has no MoE — SURVEY §2.3 listed ep out of scope — but the
driver's multi-chip contract names ep shardings, and sparse scaling is
table stakes for a modern TPU framework).

TPU-first design (GShard/Switch einsum formulation, all static shapes):
  - gating, top-k selection, and capacity-limited dispatch are dense
    einsums over a (S, E, C) one-hot dispatch tensor — no gather/scatter,
    no dynamic shapes, everything tiles onto the MXU;
  - expert weights are STACKED on a leading E axis ((E, H, U) / (E, U, H))
    so expert parallelism is nothing but a PartitionSpec("ep", ...) on
    that axis: under a mesh with an `ep` axis, GSPMD partitions the
    per-expert compute and inserts the token-exchange collectives itself
    (the scaling-book recipe — annotate shardings, let XLA insert
    collectives).  `moe_sharding_rules()` returns the rules for
    CompiledTrainStep;
  - gate math runs in f32 whatever the model dtype (softmax over E and
    the load-balance statistics are precision-sensitive); expert matmuls
    run in x.dtype.

Capacity: each expert processes at most C = ceil(capacity_factor·S·k/E)
tokens; overflow tokens are DROPPED from the MoE path (their combine
weight is zero — the residual connection around the layer carries them),
the standard Switch trade-off that keeps shapes static.

forward(x) -> (y, aux_loss): aux_loss is the Switch load-balance term
(E · Σ_e fraction_tokens_e · mean_prob_e, ≥ 1 at perfect balance); add
`aux_loss_weight * aux_loss` to the training objective.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..gluon.block import HybridBlock
from ..ndarray import ops

__all__ = ["MoEFFN", "moe_sharding_rules"]


def moe_sharding_rules():
    """Expert-parallel rules: the stacked expert axis shards over `ep`;
    the gate is replicated.  Compose with bert_sharding_rules()-style tp
    rules for the dense sublayers of a surrounding model."""
    return [
        (r"expert_w1$", P("ep", None, None)),
        (r"expert_b1$", P("ep", None)),
        (r"expert_w2$", P("ep", None, None)),
        (r"expert_b2$", P("ep", None)),
        (r"gate_weight$", P(None, None)),
    ]


def _moe_forward(x, gw, w1, b1, w2, b2, *, top_k, capacity, act):
    """Core routing + expert compute on flattened tokens (S, U)."""
    S, U = x.shape
    E = w1.shape[0]
    xf32 = x.astype(jnp.float32)
    logits = xf32 @ gw.astype(jnp.float32).T                  # (S, E)
    probs = jax.nn.softmax(logits, axis=-1)

    combine = jnp.zeros((S, E, capacity), jnp.float32)
    dispatch = jnp.zeros((S, E, capacity), jnp.bool_)
    masked = probs
    gates, masks = [], []
    for _ in range(top_k):
        idx = jnp.argmax(masked, axis=-1)                     # (S,)
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)    # (S, E)
        gates.append(jnp.sum(probs * onehot, axis=-1))        # (S,)
        masks.append(onehot)
        masked = masked * (1.0 - onehot)
    if top_k > 1:
        # renormalize the selected gates (the GShard top-2 convention)
        denom = sum(gates) + 1e-9
        gates = [g / denom for g in gates]
    # top-1 keeps the RAW router prob (Switch): y = p_i · expert_i(x) is
    # exactly what makes the router differentiable through the task loss
    # — renormalizing would pin the weight at ~1 and starve the gate of
    # gradient

    # positions within each expert: cumulative count over the token axis,
    # later selections queue after ALL first-choice tokens (priority to
    # the k=0 picks, the Switch/GShard behavior).  int32 counts: an f32
    # cumsum silently merges slots once an expert has seen > 2^24 tokens
    # (pod-scale global batches get there)
    prev = jnp.zeros((E,), jnp.int32)
    for g, m in zip(gates, masks):
        mi = m.astype(jnp.int32)
        pos = jnp.cumsum(mi, axis=0) - mi + prev[None, :]     # (S, E)
        within = (pos < capacity) & (mi > 0)
        posi = jnp.clip(pos, 0, capacity - 1)
        oh_c = jax.nn.one_hot(posi, capacity, dtype=jnp.float32)
        sel = within[..., None] * oh_c                        # (S, E, C)
        combine = combine + g[:, None, None] * sel
        dispatch = dispatch | (sel > 0)
        prev = prev + jnp.sum(mi, axis=0)

    dspf = dispatch.astype(x.dtype)
    expert_in = jnp.einsum("sec,su->ecu", dspf, x)            # (E, C, U)
    h = jnp.einsum("ecu,ehu->ech", expert_in, w1) + \
        b1[:, None, :].astype(x.dtype)
    h = act(h)
    eo = jnp.einsum("ech,euh->ecu", h, w2) + \
        b2[:, None, :].astype(x.dtype)
    y = jnp.einsum("sec,ecu->su", combine.astype(x.dtype), eo)

    # Switch load-balance auxiliary: fraction of tokens routed to each
    # expert (first choice) x mean gate prob, scaled by E
    frac = jnp.mean(masks[0], axis=0)                         # (E,)
    mean_prob = jnp.mean(probs, axis=0)                       # (E,)
    aux = E * jnp.sum(frac * mean_prob)
    return y.astype(x.dtype), aux.astype(jnp.float32)


class MoEFFN(HybridBlock):
    """Sparse FFN: top-k gated mixture of `num_experts` two-layer MLPs.

    forward(x: (..., units)) -> (y: (..., units), aux_loss: scalar).
    Under a mesh with an `ep` axis (CompiledTrainStep with
    moe_sharding_rules()), experts shard across devices."""

    def __init__(self, units, hidden_size, num_experts, top_k=2,
                 capacity_factor=1.25, activation="gelu", **kwargs):
        super().__init__(**kwargs)
        if top_k not in (1, 2):
            raise ValueError("top_k must be 1 (Switch) or 2 (GShard)")
        self._units = units
        self._hidden = hidden_size
        self._E = num_experts
        self._k = top_k
        self._cf = float(capacity_factor)
        self._act_name = activation
        self.gate_weight = self.params.get(
            "gate_weight", shape=(num_experts, units))
        self.expert_w1 = self.params.get(
            "expert_w1", shape=(num_experts, hidden_size, units))
        self.expert_b1 = self.params.get(
            "expert_b1", shape=(num_experts, hidden_size),
            init="zeros")
        self.expert_w2 = self.params.get(
            "expert_w2", shape=(num_experts, units, hidden_size))
        self.expert_b2 = self.params.get(
            "expert_b2", shape=(num_experts, units), init="zeros")

    def hybrid_forward(self, F, x, gate_weight, expert_w1, expert_b1,
                       expert_w2, expert_b2):
        import math
        shape = x.shape
        S = 1
        for d in shape[:-1]:
            S *= d
        capacity = max(1, math.ceil(self._cf * S * self._k / self._E))
        if self._act_name == "gelu":
            # match F.gelu (exact erf; jax.nn.gelu defaults to the tanh
            # approximation, which is the separate gelu_tanh op here)
            act = lambda v: jax.nn.gelu(v, approximate=False)
        else:
            act = getattr(jax.nn, self._act_name)

        def fn(xa, gw, w1, b1, w2, b2):
            flat = xa.reshape((S, shape[-1]))
            y, aux = _moe_forward(flat, gw, w1, b1, w2, b2,
                                  top_k=self._k, capacity=capacity,
                                  act=act)
            return y.reshape(shape), aux

        return ops._apply(fn, [x, gate_weight, expert_w1, expert_b1,
                               expert_w2, expert_b2], "MoEFFN")

    def __repr__(self):
        return (f"MoEFFN(units={self._units}, hidden={self._hidden}, "
                f"experts={self._E}, top_k={self._k}, "
                f"capacity_factor={self._cf})")
