"""Ulysses sequence parallelism: all-to-all head-sharded attention
(SURVEY §5.7 — the second long-context strategy next to ring attention;
DeepSpeed-Ulysses, PAPERS.md).

Design: activations arrive sequence-sharded (each of the n `sp` devices
holds T/n positions of every head).  One tiled `lax.all_to_all` per q/k/v
re-shards to HEAD-sharded (each device holds H/n heads over the FULL
sequence), attention for those heads runs entirely locally — which means
the Pallas flash kernel (full-T blockwise, MXU-sized matmuls) instead of
ring's n-step streamed blocks — and one all-to-all brings the output back
to sequence-sharded.  Communication is 4 activation-sized all-to-alls per
layer vs ring's n K/V ppermute hops; compute is one big local attention vs
n small ones.  Ring wins when T/n is still large and H < n; Ulysses wins
on MXU efficiency when H % n == 0 (the usual case: 12-128 heads, sp ≤ 8).

Trade-off table (pick with `set_sp_strategy` / the `sp_strategy` arg):
  ring    — no head-count constraint, K/V memory O(T/n) per device
  ulysses — needs H % n == 0, local flash kernel, fewer comm hops
"""
from __future__ import annotations

import functools


import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .ring_attention import _count, _sp_valid_seed, local_flash_attention

__all__ = ["ulysses_attention", "set_sp_strategy", "get_sp_strategy"]

_SP_STRATEGY = "ring"  # module default: no head-divisibility constraint


def set_sp_strategy(strategy):
    """Select the sequence-parallel attention strategy ('ring' or
    'ulysses') used by `parallel.attention` when the mesh has an `sp`
    axis.  Returns the previous value."""
    global _SP_STRATEGY
    if strategy not in ("ring", "ulysses"):
        raise ValueError("sp strategy must be 'ring' or 'ulysses'")
    prev, _SP_STRATEGY = _SP_STRATEGY, strategy
    return prev


def get_sp_strategy():
    return _SP_STRATEGY


def _ulysses_body(q, k, v, valid, seed, bias, *, axis_name, causal,
                  rate, masked, dropped, biased, key_axes=()):
    """Runs inside shard_map.  q/k/v: LOCAL sequence blocks (B, H, Tb, D).
    all_to_all → (B, H/n, T, D) head shards → one full-T local attention →
    all_to_all back."""
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    # tiled all_to_all: split the head axis n ways, concat sequence axis
    qh = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)                       # (B, H/n, T, D)
    kh = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    key = None
    if dropped:
        # tpumx-lint: disable=determinism -- key is a pure function of the
        # caller-provided seed input (traced), not a hidden fresh stream
        key = jax.random.PRNGKey(seed[0])
        for ax in key_axes:
            key = jax.random.fold_in(key, lax.axis_index(ax))
        key = jax.random.fold_in(key, my_idx)
    b_blk = None
    if biased:
        # bias arrives with full rows/cols; slice MY head group when it
        # carries a head axis
        hb = bias.shape[1]
        if hb > 1:
            hn = hb // n
            b_blk = lax.dynamic_slice_in_dim(bias, my_idx * hn, hn, axis=1)
        else:
            b_blk = bias
    # the local full-T attention goes through local_flash_attention: on
    # TPU with tile-friendly shapes that is the Pallas flash kernel
    # (blockwise, O(T) score memory — the reason ulysses wins on MXU
    # efficiency); off-TPU / unsupported shapes take the dense path.
    # NB keys: local_flash_attention derives its kernel seed from the
    # already per-device-folded key, so head groups draw independent masks
    out = local_flash_attention(
        qh, kh, vh, causal=causal,
        valid_length=valid if masked else None,
        dropout_rate=rate if dropped else 0.0,
        dropout_key=key, bias=b_blk)                      # (B, H/n, T, D)
    # back to sequence-sharded: split T, concat heads
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)                     # (B, H, Tb, D)


def ulysses_attention(q, k, v, mesh, axis_name="sp", causal=False,
                      q_spec=None, valid_length=None, dropout_rate=0.0,
                      dropout_key=None, bias=None, batch_axes=("dp", "tp")):
    """All-to-all sequence-parallel attention.  Same contract as
    `ring_attention`: q/k/v are GLOBAL (B, H, T, D) arrays with T sharded
    over `axis_name`; returns output with the same sharding.  Requires
    H % mesh.shape[axis_name] == 0 (raises otherwise — `attention()`
    falls back to ring for such models)."""
    from jax.experimental.shard_map import shard_map

    def present(ax):
        # size-1 axes shard nothing — treat as absent so e.g. tp=1 meshes
        # don't poison the head slot of the spec
        return ax in mesh.axis_names and mesh.shape[ax] > 1

    if not present(axis_name):
        return local_flash_attention(q, k, v, causal=causal,
                                     valid_length=valid_length,
                                     dropout_rate=dropout_rate,
                                     dropout_key=dropout_key, bias=bias)
    n = mesh.shape[axis_name]
    H = q.shape[1]
    if H % n:
        raise ValueError(
            f"ulysses_attention: heads ({H}) must divide by sp ({n}); "
            "use ring attention for this model")
    bax, hax = (tuple(batch_axes) + (None, None))[:2]
    spec = q_spec or P(bax if bax and present(bax) else None,
                       hax if hax and present(hax) else None,
                       axis_name, None)
    if spec[1] is not None:
        raise ValueError(
            "ulysses_attention: the head axis cannot also be mesh-sharded "
            f"(spec {spec}); all-to-all re-shards heads over {axis_name}")
    dropped = dropout_rate > 0.0 and dropout_key is not None
    masked = valid_length is not None
    biased = bias is not None
    _count("ulysses", f"sp={n} shape={q.shape}")
    valid, seed, vspec = _sp_valid_seed(q, masked, dropped, valid_length,
                                        dropout_key, spec)
    bias_arr = bias if biased else jnp.zeros((1, 1, 1, 1), q.dtype)
    # bias: rows and columns stay WHOLE (each device attends over full T
    # after the all-to-all); batch follows q's batch axis when present
    bspec = P(spec[0] if biased and bias_arr.shape[0] > 1 else None,
              None, None, None)
    key_axes = tuple(ax for ax in (spec[0],) if ax is not None)
    fn = shard_map(
        functools.partial(_ulysses_body, axis_name=axis_name, causal=causal,
                          rate=float(dropout_rate),
                          masked=masked, dropped=dropped, biased=biased,
                          key_axes=key_axes),
        mesh=mesh, in_specs=(spec, spec, spec, vspec, P(None), bspec),
        out_specs=spec, check_rep=False)
    return fn(q, k, v, valid, seed, bias_arr)
