"""Device context: the `mx.cpu() / mx.gpu(i) / mx.tpu(i)` layer.

TPU-native analog of the reference's Context (REF:include/mxnet/base.h,
REF:python/mxnet/context.py).  A Context is a *logical* device handle that
resolves to a concrete `jax.Device`; `tpu` is the accelerator type and `gpu`
is kept as a compatibility alias so reference-era scripts (`mx.gpu(0)`) run
unchanged on TPU.  Thread-local "current context" nesting via `with ctx:`
matches the reference semantics.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context", "num_gpus", "num_tpus"]

_DEVTYPE_ALIASES = {
    "cpu": "cpu",
    "cpu_pinned": "cpu",   # pinned host memory has no TPU distinction; alias to cpu
    "cpu_shared": "cpu",   # POSIX-shm sharing is a DataLoader detail handled host-side
    "gpu": "tpu",          # compatibility alias: mx.gpu(i) -> accelerator i
    "tpu": "tpu",
}


class Context:
    """Logical device. ``device_type`` in {cpu, tpu, gpu(alias), cpu_pinned, cpu_shared}."""

    _tls = threading.local()
    _default = None

    def __init__(self, device_type, device_id=0):
        if device_type not in _DEVTYPE_ALIASES:
            raise ValueError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- resolution to a concrete jax.Device ---------------------------------
    @property
    def kind(self):
        return _DEVTYPE_ALIASES[self.device_type]

    def jax_device(self):
        """Resolve to a concrete jax.Device (lazily; raises if id out of range).

        Indexes the *process-local* device list: under multi-process SPMD
        (jax.distributed) `cpu(0)`/`tpu(0)` means this worker's first device —
        global devices owned by other processes are not addressable."""
        kind = self.kind
        if kind == "tpu":
            devs = _accelerator_devices()
            if not devs:
                raise RuntimeError("no accelerator devices visible to JAX")
            if self.device_id >= len(devs):
                raise RuntimeError(
                    f"device id {self.device_id} out of range ({len(devs)} accelerator(s))"
                )
            return devs[self.device_id]
        try:
            return jax.local_devices(backend="cpu")[self.device_id]
        except RuntimeError:
            return jax.local_devices()[0]  # CPU backend absent: use default

    # -- `with ctx:` ---------------------------------------------------------
    def __enter__(self):
        stack = getattr(Context._tls, "stack", None)
        if stack is None:
            stack = Context._tls.stack = []
        stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._tls.stack.pop()
        return False

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.kind == other.kind
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.kind, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"


def _accelerator_devices():
    """This process's non-CPU jax devices; empty list when running CPU-only."""
    devs = jax.local_devices()
    accel = [d for d in devs if d.platform != "cpu"]
    return accel


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def gpu(device_id=0):
    """Compatibility alias for accelerator context (maps to TPU chip i)."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def num_gpus():
    return len(_accelerator_devices())


def num_tpus():
    return len(_accelerator_devices())


def default_context():
    """Accelerator 0 if present, else cpu — the implicit creation context."""
    if Context._default is None:
        Context._default = tpu(0) if _accelerator_devices() else cpu(0)
    return Context._default


def current_context():
    stack = getattr(Context._tls, "stack", None)
    if stack:
        return stack[-1]
    return default_context()
