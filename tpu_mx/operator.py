"""Custom operators: user-defined ops with Python forward/backward
(REF:src/operator/custom/custom.cc, REF:python/mxnet/operator.py).

The reference integrates Python CustomOps into its engine via registered
callbacks; here the imperative tape plays the engine's role, so a custom
op is a tape node whose pullback calls the user's ``backward``.  The same
three-class shape is kept — ``CustomOp`` (kernels), ``CustomOpProp``
(shape/type inference + op metadata), ``register`` — and invocation via
``mx.nd.Custom(*args, op_type=name)``.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered"]

_registry = {}


class CustomOp:
    """Base class: override ``forward`` and ``backward``.  Use
    ``self.assign(dst, req, src)`` to honor the write/add/null grad_req
    protocol like the reference."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    @staticmethod
    def assign(dst, req, src):
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src
        else:
            raise MXNetError(f"unknown req {req!r}")


class CustomOpProp:
    """Metadata provider: shapes/dtypes/arg names + op factory."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, shapes, dtypes):
        raise NotImplementedError

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps


def register(reg_name):
    """Class decorator: ``@mx.operator.register("my_op")`` on a
    CustomOpProp subclass."""
    def wrap(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register() expects a CustomOpProp subclass")
        _registry[reg_name] = prop_cls
        return prop_cls
    return wrap


def get_all_registered():
    return dict(_registry)


def _invoke_custom(args, op_type, **op_params):
    """Imperative entry used by mx.nd.Custom — builds the op, runs forward,
    and records a tape node whose pullback runs the user's backward."""
    from . import autograd
    from .ndarray import NDArray
    from .context import current_context
    import jax.numpy as jnp

    if op_type not in _registry:
        raise MXNetError(
            f"custom op {op_type!r} is not registered "
            f"(known: {sorted(_registry)})")
    prop = _registry[op_type](**op_params)

    in_shapes = [tuple(a.shape) for a in args]
    in_types = [a.dtype for a in args]
    _, out_shapes, aux_shapes = prop.infer_shape(list(in_shapes))
    _, out_types, _ = prop.infer_type(list(in_types))
    op = prop.create_operator(current_context(), in_shapes, in_types)

    in_data = list(args)
    out_data = [NDArray(jnp.zeros(s, t))
                for s, t in zip(out_shapes, out_types)]
    aux = [NDArray(jnp.zeros(s, "float32")) for s in aux_shapes]

    with autograd.pause():
        op.forward(is_train=autograd.is_recording(),
                   req=["write"] * len(out_data),
                   in_data=in_data, out_data=out_data, aux=aux)

    if autograd._needs_tape(in_data):
        single_out = len(out_data) == 1

        def vjp_fn(out_ct):
            cts = (out_ct,) if single_out else tuple(out_ct)
            in_grad = [NDArray(jnp.zeros(s, t))
                       for s, t in zip(in_shapes, in_types)]
            with autograd.pause():
                op.backward(req=["write"] * len(in_grad),
                            out_grad=[NDArray(c) for c in cts],
                            in_data=in_data, out_data=out_data,
                            in_grad=in_grad, aux=aux)
            return tuple(g._data for g in in_grad)

        autograd._record_op(vjp_fn, list(in_data), list(out_data),
                            name=f"Custom[{op_type}]")

    return out_data[0] if len(out_data) == 1 else out_data
