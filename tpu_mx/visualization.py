"""mx.viz — network visualization (REF:python/mxnet/visualization.py:
print_summary + plot_network).

`print_summary` walks the Symbol DAG in topological order and prints the
reference's table: layer name, op, output shape (via `infer_shape_partial`
on the provided input shapes), parameter count per layer and totals.
`plot_network` emits a graphviz Digraph when the `graphviz` package is
present and raises a clear pointer otherwise (this image ships without
it — the textual summary is the supported path).
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .symbol.symbol import _topo

__all__ = ["print_summary", "plot_network"]


def _node_output_shapes(sym, shape_kwargs):
    """name -> output shape for every internal output, best-effort."""
    internals = sym.get_internals()
    try:
        _, out_shapes, _ = internals.infer_shape_partial(**shape_kwargs)
    except Exception:
        out_shapes = None
    if out_shapes is None:  # partial inference gave up entirely
        return {}
    shapes = {}
    for s, shp in zip(internals, out_shapes):
        if shp is not None:
            shapes.setdefault(s.name, tuple(int(v) for v in shp))
    return shapes


def print_summary(symbol, shape=None, line_length=98, positions=None):
    """Print the layer table (REF visualization.py:print_summary).

    shape: dict of input name -> shape, e.g. {"data": (1, 3, 224, 224)} —
    needed for output shapes and parameter counts; without it the topology
    still prints with blanks.  Returns the total parameter count."""
    shape = shape or {}
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    cols = [int(line_length * p) for p in positions]
    shapes = _node_output_shapes(symbol, shape) if shape else {}
    # param shapes via full inference on the arguments
    arg_shapes = {}
    if shape:
        try:
            a_shapes, _, aux_shapes = symbol.infer_shape_partial(**shape)
            arg_shapes = dict(zip(symbol.list_arguments(), a_shapes))
            arg_shapes.update(zip(symbol.list_auxiliary_states(), aux_shapes))
        except Exception:
            pass

    def row(fields):
        line = ""
        for text, stop in zip(fields, cols):
            line = (line + str(text))[:stop].ljust(stop)
        print(line)

    print("=" * line_length)
    row(["Layer (type)", "Output Shape", "Param #", "Previous Layer"])
    print("=" * line_length)
    total = 0
    order = _topo(symbol._entries)
    for n in order:
        if n.is_variable():
            continue
        prev = ",".join(c.name for c, _ in n.inputs if not c.is_variable())
        params = 0
        for (child, _i) in n.inputs:
            if child.is_variable() and child.name in arg_shapes and \
                    child.name not in shape:
                shp = arg_shapes[child.name]
                if shp:
                    params += int(_np.prod(shp))
        total += params
        out = shapes.get(n.name, "")
        row([f"{n.name} ({n.op})", out, params, prev])
    print("=" * line_length)
    print(f"Total params: {total}")
    print("=" * line_length)
    return total


def plot_network(symbol, title="plot", shape=None, node_attrs=None,
                 save_format="pdf"):
    """Graphviz rendering of the Symbol DAG (REF visualization.py:
    plot_network).  Requires the optional `graphviz` package; this
    environment does not ship it, so the error points to print_summary."""
    try:
        import graphviz
    except ImportError as e:
        raise MXNetError(
            "plot_network needs the 'graphviz' package, which is not "
            "installed in this environment; use "
            "tpu_mx.viz.print_summary(sym, shape=...) for the textual "
            "summary") from e
    dot = graphviz.Digraph(name=title, format=save_format)
    node_attrs = node_attrs or {"shape": "box", "fontsize": "10"}
    for n in _topo(symbol._entries):
        label = n.name if n.is_variable() else f"{n.name}\n{n.op}"
        dot.node(n.name, label=label, **node_attrs)
        for child, _ in n.inputs:
            dot.edge(child.name, n.name)
    return dot
