"""mx.libinfo (REF:src/libinfo.cc features surface): thin alias over
tpu_mx.runtime's live-probed feature list.  `features` is computed
LAZILY (module __getattr__): probing touches the jax backend, which must
not happen at import time (it would foreclose pre-init jax config like
jax.distributed.initialize)."""
from .runtime import Features, feature_list

__all__ = ["Features", "feature_list", "features", "__version__"]


def __getattr__(name):
    if name == "features":
        return feature_list()
    if name == "__version__":
        from . import __version__ as v
        return v
    raise AttributeError(name)
