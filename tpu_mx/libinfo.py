"""mx.libinfo (REF:src/libinfo.cc features surface): thin alias over
tpu_mx.runtime's live-probed feature list."""
from .runtime import Features, feature_list

__version__ = "1.0.0-tpu"

__all__ = ["Features", "feature_list", "features", "__version__"]

features = feature_list()
