"""Profiler API (REF:python/mxnet/profiler.py, REF:src/profiler/profiler.cc).

The reference brackets every engine op with timestamps and emits a
chrome://tracing JSON plus per-op aggregate statistics.  TPU-natively the
heavy lifting is ``jax.profiler`` (XLA traces viewable in Perfetto /
TensorBoard); this module keeps the reference-shaped API on top of it and
adds a host-side scope recorder so ``dumps()`` can print an aggregate
per-scope table like the reference's ``aggregate_stats.cc``.

Usage (same shape as the reference):
    mx.profiler.set_config(filename='profile.json', profile_all=True)
    mx.profiler.set_state('run')
    ... work ...
    mx.profiler.set_state('stop')
    print(mx.profiler.dumps())
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["set_config", "set_state", "pause", "resume", "dump", "dumps",
           "scope", "record_span", "Task", "Frame", "Event", "Counter",
           "Marker"]

_state = {
    "filename": "profile.json",
    "trace_dir": None,       # jax.profiler trace directory (derived from filename)
    "running": False,
    "paused": False,
    "jax_trace": False,      # whether a jax.profiler trace is active
    "profile_all": False,
}
_lock = threading.Lock()
# scope name -> [count, total_seconds, min_seconds, max_seconds]
_agg: dict[str, list] = {}
# chrome-trace events recorded host-side (scopes, markers, counters)
_events: list[dict] = []
_pid = os.getpid()


def set_config(filename="profile.json", profile_all=False, profile_symbolic=True,
               profile_imperative=True, profile_memory=False, profile_api=False,
               aggregate_stats=True, **kwargs):
    """Configure the profiler.  Mode kwargs mirror the reference; all op
    execution on TPU is captured uniformly by the XLA trace, so the
    symbolic/imperative/memory/api switches only gate host-side recording."""
    _state["filename"] = filename
    _state["profile_all"] = profile_all
    base, _ = os.path.splitext(filename)
    _state["trace_dir"] = base + "_xla_trace"


def set_state(state="stop"):
    """'run' starts profiling (including a jax.profiler/XLA device trace when
    possible); 'stop' ends it and writes the chrome-trace JSON."""
    if state == "run":
        if _state["running"]:
            return
        with _lock:
            _events.clear()
            _agg.clear()
        _state["running"], _state["paused"] = True, False
        try:
            import jax
            jax.profiler.start_trace(_state["trace_dir"] or "profile_xla_trace")
            _state["jax_trace"] = True
        except Exception:
            _state["jax_trace"] = False
    elif state == "stop":
        if not _state["running"]:
            return
        _state["running"] = False
        if _state["jax_trace"]:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            _state["jax_trace"] = False
        dump()
    else:
        raise ValueError("state must be 'run' or 'stop'")


def pause():
    """Suspend host-side recording without ending the session."""
    _state["paused"] = True


def resume():
    _state["paused"] = False


def _recording():
    return _state["running"] and not _state["paused"]


def _record_scope(name, t0, t1, category="scope"):
    with _lock:
        st = _agg.setdefault(name, [0, 0.0, float("inf"), 0.0])
        dt = t1 - t0
        st[0] += 1
        st[1] += dt
        st[2] = min(st[2], dt)
        st[3] = max(st[3], dt)
        _events.append({"name": name, "cat": category, "ph": "X",
                        "ts": t0 * 1e6, "dur": dt * 1e6,
                        "pid": _pid, "tid": threading.get_ident()})


def record_span(name, t0, t1, category="telemetry"):
    """Merge an externally-timed interval (``time.perf_counter`` endpoints)
    into the chrome-trace event stream and the aggregate table — the bridge
    ``tpu_mx.telemetry.span`` uses so telemetry spans land on the same
    Perfetto timeline as the profiler scopes and XLA annotations.  No-op
    unless the profiler is recording."""
    if _recording():
        _record_scope(name, t0, t1, category)


class scope:
    """Context manager: times a named region, forwards it to the XLA trace as
    a ``jax.profiler.TraceAnnotation``, and feeds the aggregate table."""

    def __init__(self, name):
        self.name = name
        self._ann = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        if _recording():
            try:
                import jax
                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        if _recording():
            _record_scope(self.name, self._t0, t1)
        return False


class Task:
    """Named task object (reference: profiler::ProfileTask)."""

    def __init__(self, name, domain=None):
        self.name = name
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        if self._t0 is not None and _recording():
            _record_scope(self.name, self._t0, time.perf_counter(), "task")
        self._t0 = None


class Frame(Task):
    """Named frame (reference: profiler::ProfileFrame)."""


class Event(Task):
    """Named event (reference: profiler::ProfileEvent)."""


class Counter:
    """Named monotonic counter emitted into the chrome trace
    (reference: profiler::ProfileCounter)."""

    def __init__(self, name, domain=None, value=0):
        self.name = name
        self.value = value
        self._emit()

    def _emit(self):
        if _recording():
            with _lock:
                _events.append({"name": self.name, "ph": "C",
                                "ts": time.perf_counter() * 1e6, "pid": _pid,
                                "args": {self.name: self.value}})

    def set_value(self, value):
        self.value = value
        self._emit()

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)


class Marker:
    """Instant event (reference: profiler::ProfileMarker)."""

    def __init__(self, name, domain=None):
        self.name = name

    def mark(self, scope="process"):
        if _recording():
            with _lock:
                _events.append({"name": self.name, "ph": "i",
                                "ts": time.perf_counter() * 1e6, "pid": _pid,
                                "tid": threading.get_ident(),
                                "s": {"process": "p", "thread": "t",
                                      "global": "g"}.get(scope, "p")})


def dump(finished=True):
    """Write recorded host-side events as chrome://tracing JSON to the
    configured filename.  The XLA device trace lives separately under
    ``<filename-stem>_xla_trace/`` (view with Perfetto/TensorBoard).

    Routed through ``checkpoint.atomic_write`` (tmp+fsync+rename): a crash
    mid-dump leaves the previous complete ``profile.json`` — the same
    contract every other state writer got in the durability PR."""
    with _lock:
        events = list(_events)
    from .checkpoint import atomic_write
    with atomic_write(_state["filename"], "w") as f:
        # stream — a long session's trace is large; one monolithic
        # json.dumps string would double peak memory at dump time
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)


def dumps(reset=False):
    """Return the aggregate per-scope statistics table as a string
    (reference: MXAggregateProfileStatsPrint)."""
    with _lock:
        rows = sorted(_agg.items(), key=lambda kv: -kv[1][1])
        if reset:
            _agg.clear()
    lines = ["%-40s %8s %12s %12s %12s %12s" %
             ("Name", "Calls", "Total(ms)", "Mean(ms)", "Min(ms)", "Max(ms)")]
    for name, (n, tot, mn, mx) in rows:
        lines.append("%-40s %8d %12.3f %12.3f %12.3f %12.3f" %
                     (name, n, tot * 1e3, tot / n * 1e3, mn * 1e3, mx * 1e3))
    return "\n".join(lines)


# deprecated aliases kept for reference import parity
# (REF:python/mxnet/profiler.py profiler_set_config/profiler_set_state)
def profiler_set_config(**kwargs):
    return set_config(**kwargs)


def profiler_set_state(state="stop"):
    return set_state(state)
