"""The ``mx.io`` data-iterator surface.

Capability map to the reference:
  * ``DataIter``/``DataBatch``/``DataDesc`` protocol — REF:python/mxnet/io/io.py
  * ``NDArrayIter`` (pad/discard/roll_over)       — REF:python/mxnet/io/io.py
  * ``MNISTIter``, ``CSVIter``                      — REF:src/io/iter_mnist.cc,
    REF:src/io/iter_csv.cc (C++ iters exposed through MXDataIter)
  * ``ImageRecordIter``                             — REF:src/io/iter_image_recordio_2.cc
    (multithreaded JPEG decode + augment + batch; here: a thread pool decoding
    into pinned host staging, with the native C++ chunk reader used when built)
  * ``PrefetchingIter``                             — REF:src/io/iter_prefetcher.h
    (double-buffering on a background thread so host decode overlaps device step)

TPU-first notes: iterators produce host numpy batches; transfer happens once
per batch via ``nd.array`` (→ ``jax.device_put``), and ``PrefetchingIter``
keeps the next batch decoding while the current one trains — the same
pipeline shape the reference builds with dmlc::ThreadedIter.

Deterministic resume (docs/robustness.md): every iterator here implements the
``state_dict() / load_state_dict()`` protocol — epoch cursor, shuffle
permutation and private RNG state — so a training-state capsule
(`tpu_mx/resume.py`) can restore the data stream to the exact next batch
after a crash instead of silently resetting it.  The reference had no analog
(its `do_checkpoint` was epoch-granular and stateless about data;
docs/DIVERGENCES.md #25).
"""
from __future__ import annotations

import copy as _copy
import gzip
import logging
import os
import queue
import struct
import threading
from collections import namedtuple
from concurrent.futures import ThreadPoolExecutor

import numpy as np

_logger = logging.getLogger(__name__)


def _np_rng_tuple(state):
    """Normalize a (possibly JSON-round-tripped) numpy RandomState token
    back into the exact tuple ``set_state`` wants — list elements become
    the MT19937 array / ints / float they were."""
    return (str(state[0]), np.asarray(state[1], dtype=np.uint32),
            int(state[2]), int(state[3]), float(state[4]))


def _check_state(state, cls_name):
    got = state.get("iter") if isinstance(state, dict) else None
    if got != cls_name:
        raise MXNetError(
            f"load_state_dict: state was captured from {got!r}, "
            f"not {cls_name!r} — resume must reconstruct the same pipeline")

from ..base import MXNetError, check
from .. import ndarray as nd
from ..ndarray import NDArray


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Shape/type descriptor for one input (REF io.py DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, tuple(shape))
        ret.dtype = dtype
        ret.layout = layout
        return ret

    @staticmethod
    def get_batch_axis(layout):
        return 0 if not layout else layout.find("N")


class DataBatch:
    """One batch: lists of data/label arrays plus padding bookkeeping."""

    def __init__(self, data, label=None, pad=0, index=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label if label is not None else []
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Iterator protocol (reset / next / iter_next / getdata / getlabel /
    getpad), identical surface to the reference's DataIter — plus the
    resume protocol (``state_dict``/``load_state_dict``) and lifecycle
    (``close``, context-manager) this framework adds."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    # -- deterministic-resume protocol (docs/robustness.md) -------------
    def state_dict(self):
        """Snapshot of the iterator's position/RNG, taken BETWEEN batches.
        Loading it into a freshly constructed identical iterator makes it
        produce exactly the not-yet-consumed batches (and identical
        shuffles on later resets)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement state_dict — "
            "deterministic resume is unavailable for this iterator")

    def load_state_dict(self, state):
        raise NotImplementedError(
            f"{type(self).__name__} does not implement load_state_dict")

    # -- lifecycle -------------------------------------------------------
    def close(self):
        """Release background resources (threads, file handles).
        Idempotent; the base iterator holds none."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def next(self):
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0

    @property
    def provide_data(self):
        raise NotImplementedError

    @property
    def provide_label(self):
        return []


def _as_list_of_pairs(data, default_name):
    """Normalize data=dict|list|array → [(name, ndarray)] (init_data in REF)."""
    if data is None:
        return []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [(default_name, data)]
    elif isinstance(data, (list, tuple)):
        data = [(f"{default_name}_{i}" if i else default_name, d)
                for i, d in enumerate(data)]
    elif isinstance(data, dict):
        data = sorted(data.items())
    out = []
    for k, v in data:
        arr = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
        out.append((k, arr))
    return out


class NDArrayIter(DataIter):
    """Batches over in-memory arrays with ``pad``/``discard``/``roll_over``
    last-batch handling and optional shuffling (REF io.py NDArrayIter).

    Elastic sharding (``num_workers``/``rank``; docs/robustness.md
    "Elastic fleets"): ``batch_size`` is always the GLOBAL batch.  The
    iterator advances a single global cursor through one global
    permutation and every rank slices its contiguous
    ``batch_size/num_workers`` piece out of the same global selection —
    so the global sample sequence is a pure function of (seed, global
    batch) and IDENTICAL for every world size.  That is the exact-replay
    invariant a membership change relies on: re-partition the live
    iterator with :meth:`set_shard` (or restore a v2 state into an
    iterator built with different ``(rank, num_workers)``) and the world
    keeps consuming exactly the batches the old world would have."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label", seed=None,
                 num_workers=1, rank=0):
        super().__init__(batch_size)
        self.data = _as_list_of_pairs(data, data_name)
        self.label = _as_list_of_pairs(label, label_name)
        check(self.data, "NDArrayIter needs at least one data array")
        self.num_data = self.data[0][1].shape[0]
        for k, v in self.data + self.label:
            check(v.shape[0] == self.num_data,
                  f"array {k} first dim {v.shape[0]} != {self.num_data}")
        check(last_batch_handle in ("pad", "discard", "roll_over"),
              f"bad last_batch_handle {last_batch_handle}")
        check(self.num_data >= batch_size,
              "batch_size larger than dataset")
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.global_batch_size = int(batch_size)
        self._rng = np.random.RandomState(seed) if seed is not None \
            else np.random
        self._leftover = None  # roll_over: tail carried into the next epoch
        self._global_sel = None
        self.num_workers = 1
        self.rank = 0
        self.set_shard(rank, num_workers)
        self.reset()

    def set_shard(self, rank, num_workers):
        """(rank, num_workers) re-partition of the live GLOBAL stream —
        the data-side half of a membership change.  Only the slice this
        rank delivers changes; the global cursor, permutation and RNG
        stream are untouched, so the global sample sequence continues
        exactly where it was regardless of the world size."""
        num_workers, rank = int(num_workers), int(rank)
        check(num_workers >= 1, "set_shard: num_workers must be >= 1")
        check(0 <= rank < num_workers,
              f"set_shard: rank {rank} out of range for {num_workers}")
        check(self.global_batch_size % num_workers == 0,
              f"set_shard: global batch {self.global_batch_size} not "
              f"divisible by num_workers {num_workers} — replay boundaries "
              "would shift")
        self.num_workers = num_workers
        self.rank = rank
        self.batch_size = self.global_batch_size // num_workers
        self._sel = None
        self._pad = 0

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        epoch = np.arange(self.num_data)
        if self.shuffle:
            self._rng.shuffle(epoch)
        if self.last_batch_handle == "roll_over" and self._leftover is not None:
            # last epoch's tail leads this epoch (reference roll_over contract)
            epoch = np.concatenate([self._leftover, epoch])
            self._leftover = None
        self.idx = epoch
        self.cursor = 0
        self._sel = None
        self._global_sel = None
        self._pad = 0

    def iter_next(self):
        n = len(self.idx)
        gbs = self.global_batch_size
        remaining = n - self.cursor
        if remaining <= 0:
            return False
        gpad = 0
        if remaining >= gbs:
            gsel = self.idx[self.cursor:self.cursor + gbs]
            self.cursor += gbs
        else:
            # short global tail
            if self.last_batch_handle == "discard":
                self.cursor = n
                return False
            if self.last_batch_handle == "roll_over":
                self._leftover = self.idx[self.cursor:]
                self.cursor = n
                return False
            # pad: wrap to the epoch head, report the overlap via getpad()
            gpad = gbs - remaining
            gsel = np.concatenate([self.idx[self.cursor:], self.idx[:gpad]])
            self.cursor = n
        self._global_sel = gsel
        # this rank's contiguous piece of the one global selection
        lb = self.batch_size
        lo = self.rank * lb
        self._sel = gsel[lo:lo + lb]
        # padded (wrapped) ids occupy the global selection's tail; this
        # rank's pad is however much of that tail lands in its piece
        self._pad = max(0, min(lb, lo + lb - (gbs - gpad))) if gpad else 0
        return True

    def global_batch_ids(self):
        """Sample ids of the last GLOBAL batch — identical for every rank
        of any world size at the same cursor.  This is the sample-id
        ledger the elastic-fleet churn proof compares batch-by-batch
        (docs/robustness.md)."""
        return (None if self._global_sel is None
                else np.asarray(self._global_sel).copy())

    def state_dict(self):
        """Position + this epoch's permutation + the private RNG stream
        (the data itself is reconstructed by the constructor).

        All position fields are in GLOBAL sample space.  Unsharded
        iterators emit the v1 layout unchanged; sharded ones emit v2,
        adding the ``shard`` map — v2 states re-partition on load
        (different ``(rank, num_workers)`` is legal), v1 states do not
        carry enough to prove they were whole-stream snapshots, so
        loading one into a sharded iterator refuses loudly (see
        :meth:`load_state_dict`)."""
        state = {"iter": type(self).__name__,
                 "version": 1 if self.num_workers == 1 else 2,
                 "cursor": int(self.cursor),
                 "idx": np.asarray(self.idx).copy(),
                 "leftover": (None if self._leftover is None
                              else np.asarray(self._leftover).copy()),
                 "rng": self._rng.get_state()}
        if self.num_workers != 1:
            state["shard"] = {"num_workers": self.num_workers,
                              "rank": self.rank,
                              "global_batch": self.global_batch_size}
        return state

    def load_state_dict(self, state):
        """Adopt a captured GLOBAL stream position.  The state's shard
        placement is NOT adopted — this iterator keeps its own
        ``(rank, num_workers)`` and reslices the global stream, which is
        exactly the N→M re-partition path a membership change needs.
        Constraints, checked loudly instead of guessed:

        - a v2 state must have been captured at the same GLOBAL batch
          size (otherwise replay boundaries shift);
        - a v1 state (no shard map) is only accepted by an unsharded
          iterator — a v1 capture from an old N-world run was a
          per-worker LOCAL stream and cannot be re-partitioned.  To bless
          a v1 state you know was whole-stream, load it unsharded, then
          :meth:`set_shard`.
        """
        _check_state(state, type(self).__name__)
        shard = state.get("shard")
        if shard is not None:
            captured = int(shard.get("global_batch", -1))
            if captured != self.global_batch_size:
                raise MXNetError(
                    f"load_state_dict: state was captured at global batch "
                    f"{captured}, this iterator uses "
                    f"{self.global_batch_size} — replay boundaries would "
                    "shift; rebuild with the captured global batch")
        elif self.num_workers != 1:
            raise MXNetError(
                "load_state_dict: v1 iterator state has no shard map — it "
                "may be a per-worker LOCAL stream and cannot be "
                f"re-partitioned to num_workers={self.num_workers}; load "
                "it into an unsharded iterator (then set_shard) if it is "
                "known to be whole-stream")
        self.idx = np.asarray(state["idx"], dtype=np.intp)
        self.cursor = int(state["cursor"])
        lo = state.get("leftover")
        self._leftover = None if lo is None else np.asarray(lo, dtype=np.intp)
        self._rng.set_state(_np_rng_tuple(state["rng"]))
        self._sel = None
        self._global_sel = None
        self._pad = 0

    def _take(self, arrs):
        return [nd.array(v[self._sel]) for _, v in arrs]

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        return self._pad


class ResizeIter(DataIter):
    """Caps/extends an iterator to exactly ``size`` batches per epoch
    (REF io.py ResizeIter — used to equalize epoch lengths)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def state_dict(self):
        return {"iter": "ResizeIter", "version": 1, "cur": int(self.cur),
                "internal": self.data_iter.state_dict()}

    def load_state_dict(self, state):
        _check_state(state, "ResizeIter")
        self.cur = int(state["cur"])
        self.data_iter.load_state_dict(state["internal"])
        self.current_batch = None

    def close(self):
        self.data_iter.close()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Runs the wrapped iterator(s) on a background thread with a bounded
    queue — REF:src/io/iter_prefetcher.h's double buffering, host-side.

    Lifecycle: the prefetch thread's queue puts are stop-aware, so
    ``close()`` (or leaving a ``with`` block, or ``reset``) always joins
    the thread — a crashed epoch can no longer leak a prefetch thread
    blocked on a full queue past supervisor degrade.

    Resume: ``state_dict()`` drains the worker first (already-produced
    batches stay buffered for the live consumer and are *re-produced* on
    restore — nothing in flight is lost) and records the wrapped
    iterators' epoch-start state plus how many batches the consumer has
    taken; ``load_state_dict`` restores the epoch-start state and
    fast-forwards that many batches, which is exact because the wrapped
    iterators are deterministic under their restored RNG state."""

    def __init__(self, iters, depth=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.depth = depth
        self._queue = None
        self._thread = None
        self._buffered = []      # drained-but-undelivered queue items
        self._delivered = 0      # batches handed to the consumer this epoch
        self._exhausted = False
        self._epoch_state = self._capture_epoch_state()
        self._start()

    @property
    def provide_data(self):
        return sum([i.provide_data for i in self.iters], [])

    @property
    def provide_label(self):
        return sum([i.provide_label for i in self.iters], [])

    def _capture_epoch_state(self):
        try:
            return [it.state_dict() for it in self.iters]
        except NotImplementedError:
            return None  # wrapped iter can't snapshot: resume unavailable

    def _start(self):
        self._queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._overflow = []  # item in the worker's hand when a stop landed
        stop, q, overflow = self._stop, self._queue, self._overflow

        def put(item):
            # stop-aware put: a full queue never wedges the worker past a
            # close()/reset().  An already-produced item must not be
            # dropped though — the wrapped iterator advanced past it — so
            # a stopped handoff stashes it for _pause to collect.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            overflow.append(item)
            return False

        def worker():
            try:
                while not stop.is_set():
                    batches = []
                    for it in self.iters:
                        batches.append(it.next())
                    if not put(self._transform(batches)):
                        return
            except StopIteration:
                put(None)
            except Exception as e:  # surface errors on the consumer side
                put(e)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def _transform(self, batches):
        """Hook run on the prefetch thread before a batch is queued
        (DevicePrefetchIter stages batches onto the device here)."""
        return batches

    def _drain(self):
        if self._queue is None:
            return
        try:
            while True:
                self._buffered.append(self._queue.get_nowait())
        except queue.Empty:
            pass

    def _pause(self, timeout=None, detach=False):
        """Stop and join the prefetch thread, preserving already-produced
        items in order.  Returns True when the thread is down.

        On timeout (wrapped iterator wedged inside ``next()``): with
        ``detach=True`` the daemon thread is abandoned — it exits on its
        own once the blocked call returns, because its stop flag is set
        and its queue/overflow are orphaned with it — else the caller
        decides (``state_dict`` raises rather than race a live worker)."""
        t = self._thread
        if t is None:
            return True
        self._stop.set()
        import time as _time
        deadline = None if timeout is None else _time.time() + timeout
        while t.is_alive():
            self._drain()  # unblock a put-in-progress
            t.join(timeout=0.1)
            if deadline is not None and _time.time() > deadline:
                _logger.warning(
                    "PrefetchingIter: prefetch thread did not stop within "
                    "%.1fs (wrapped iterator blocked?)%s", timeout,
                    " — abandoning the daemon thread" if detach else "")
                if detach:
                    self._thread = None
                    self._queue = None
                return False
        self._drain()
        # queued items were produced before the worker's in-hand one, so
        # the overflow goes last — order preserved for the live consumer
        self._buffered.extend(self._overflow)
        self._overflow = []
        self._thread = None
        self._queue = None
        return True

    def _pause_for_snapshot(self):
        """Bounded pause for state_dict/load_state_dict: a wedged worker
        must surface as a loud error, not an eternal hang (the supervisor
        watchdog does not wrap capsule writes) and must never race the
        restore's own use of the wrapped iterators."""
        if not self._pause(timeout=30.0):
            raise MXNetError(
                "PrefetchingIter: prefetch worker did not stop within 30s "
                "(wrapped iterator wedged in next()) — cannot snapshot or "
                "restore while it may still be advancing the stream")

    def close(self):
        """Join the background prefetch thread and close the wrapped
        iterators.  Idempotent; also runs on ``with``-block exit so an
        exception unwinding the training loop cannot leak the thread."""
        self._pause(timeout=10.0, detach=True)
        self._buffered = []
        self._exhausted = True
        for it in self.iters:
            it.close()

    def __del__(self):  # best effort — close() is the contract
        try:
            self._pause(timeout=0.5)
        except BaseException:
            pass

    def reset(self):
        # bounded, as the pre-close()-era join was: a wedged worker is
        # detached (its stop flag is set; it exits when next() returns)
        # rather than hanging the training loop's epoch boundary forever
        self._pause(timeout=10.0, detach=True)
        self._buffered = []
        for it in self.iters:
            it.reset()
        self._delivered = 0
        self._exhausted = False
        self._epoch_state = self._capture_epoch_state()
        self._start()

    def state_dict(self):
        """Drain-then-snapshot: pause the worker (queued batches stay
        buffered for the live consumer — not lost, and re-produced on
        restore since they were never delivered), then record epoch-start
        state + delivered count.  The worker restarts lazily on the next
        ``iter_next``."""
        if self._epoch_state is None:
            raise NotImplementedError(
                "PrefetchingIter: wrapped iterator(s) do not implement "
                "state_dict — deterministic resume unavailable")
        self._pause_for_snapshot()
        if self._exhausted and not self._buffered:
            # epoch boundary (the per-epoch capsule point): the worker has
            # exited and nothing is in flight, so the wrapped iterators'
            # CURRENT state is exact — store it with delivered=0 and spare
            # the restore a whole epoch of fast-forward decode/replay
            return {"iter": type(self).__name__, "version": 1,
                    "delivered": 0, "exhausted": True,
                    "iters": [it.state_dict() for it in self.iters]}
        return {"iter": type(self).__name__, "version": 1,
                "delivered": int(self._delivered),
                "exhausted": bool(self._exhausted),
                "iters": _copy.deepcopy(self._epoch_state)}

    def load_state_dict(self, state):
        _check_state(state, type(self).__name__)
        self._pause_for_snapshot()
        self._buffered = []
        for it, s in zip(self.iters, state["iters"]):
            it.load_state_dict(s)
        self._epoch_state = _copy.deepcopy(state["iters"])
        delivered = int(state.get("delivered", 0))
        for _ in range(delivered):
            # fast-forward replay: the wrapped iterators deterministically
            # re-produce (and we discard) the batches the consumer already
            # trained on, landing the stream on the exact next batch
            for it in self.iters:
                it.next()
        self._delivered = delivered
        self._exhausted = bool(state.get("exhausted", False))
        # worker restarts lazily on the next iter_next

    def iter_next(self):
        if self._exhausted:  # worker exited; a blocking get() would hang
            return False
        if self._buffered:
            item = self._buffered.pop(0)
        else:
            if self._thread is None:
                self._start()  # paused by a snapshot/restore: resume
            item = self._queue.get()
        if item is None:
            self._exhausted = True
            return False
        if isinstance(item, Exception):
            self._exhausted = True
            self._pause(timeout=5.0)  # the worker already exited: join it
            raise item
        self._batches = item
        self._delivered += 1
        return True

    def next(self):
        if not self.iter_next():
            raise StopIteration
        b = self._batches[0]
        if len(self._batches) > 1:
            return DataBatch(
                sum([x.data for x in self._batches], []),
                sum([x.label for x in self._batches], []),
                pad=b.pad, index=b.index)
        return b

    def getdata(self):
        return sum([x.data for x in self._batches], [])

    def getlabel(self):
        return sum([x.label for x in self._batches], [])

    def getpad(self):
        return self._batches[0].pad


class DevicePrefetchIter(PrefetchingIter):
    """Device-feed double buffering: the prefetch thread eagerly
    `jax.device_put`s every batch (optionally casting the data to the
    compute dtype first) so the host→HBM transfer of batch k+1 overlaps
    the device compute of batch k.  This is the H2D half of the
    reference's prefetcher story (REF:src/io/iter_prefetcher.h fed
    cpu_pinned buffers that the engine copied async) done the JAX way:
    `device_put` is itself asynchronous, the win is ISSUING it a batch
    early instead of on the training loop's critical path.

        it = mx.io.DevicePrefetchIter(train_iter, cast_data="bfloat16")
        for batch in it:           # batch.data already on-device, bf16
            step.step(batch.data[0], batch.label[0])

    `device` accepts a `jax.sharding.Sharding` too — REQUIRED when the
    consuming step runs over a mesh: pass the step's batch sharding
    (e.g. ``NamedSharding(mesh, P("dp"))``) so batches arrive already
    laid out; the single-device default would otherwise commit every
    batch to ``jax.devices()[0]`` and fight the meshed jit's
    ``in_shardings``.
    """

    def __init__(self, iters, depth=2, device=None, cast_data=None,
                 normalize=None, normalize_axis=-1):
        """`normalize=(mean, std)` applies `(x - mean) / std` ON DEVICE
        in f32, BEFORE the `cast_data` cast (casting first would quantize
        mean/std themselves at bf16), broadcast along `normalize_axis`
        (channel axis: -1 for NHWC feeds, 1 for NCHW).  Pair it with an
        `ImageRecordIter(output_dtype="uint8")` feed: the host ships raw
        pixels (4x fewer bytes over the interconnect) and this prefetch
        thread's asynchronous device op does the arithmetic the C++
        pipeline no longer has to."""
        self._device = device
        self._cast = cast_data
        self._norm = None
        if normalize is not None:
            mean, std = normalize
            self._norm = (np.asarray(mean, np.float32),
                          np.asarray(std, np.float32), int(normalize_axis))
        super().__init__(iters, depth=depth)

    def _transform(self, batches):
        import jax
        dev = self._device or jax.devices()[0]

        def place(arr, cast, is_data=False):
            x = arr._data if isinstance(arr, nd.NDArray) else arr
            out = jax.device_put(x, dev)
            norm = self._norm if is_data else None
            if norm is not None:
                # normalize in f32 FIRST, then apply the requested cast:
                # normalizing after a bf16 cast would quantize mean/std
                # themselves (123.68 -> 124.0 at bf16's quantum) and bias
                # every pixel vs the host-normalized f32 feed
                mean, std, ax = norm
                shape = [1] * out.ndim
                ax = ax % out.ndim
                shape[ax] = mean.size
                out = (out.astype(np.float32) - mean.reshape(shape)) \
                    / std.reshape(shape)
            if cast is not None:
                out = out.astype(cast)  # on-device cast, still async
            return nd.NDArray(out)

        staged = []
        for b in batches:
            staged.append(DataBatch(
                [place(d, self._cast, is_data=True) for d in b.data],
                [place(l, None) for l in b.label],
                pad=b.pad, index=b.index,
                provide_data=b.provide_data,
                provide_label=b.provide_label))
        return staged


def _read_idx_ubyte(path):
    """Read an MNIST idx-ubyte file (REF:src/io/iter_mnist.cc ReadInt loop)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


class MNISTIter(NDArrayIter):
    """MNIST idx-ubyte reader (REF:src/io/iter_mnist.cc).  Produces
    ``(N,1,28,28)`` float32 in [0,1] (or flat ``(N,784)``)."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 silent=True, seed=0, **kwargs):
        imgs = _read_idx_ubyte(image).astype(np.float32) / 255.0
        labels = _read_idx_ubyte(label).astype(np.float32)
        imgs = imgs.reshape(len(imgs), -1) if flat else imgs[:, None, :, :]
        super().__init__(imgs, labels, batch_size=batch_size, shuffle=shuffle,
                         last_batch_handle="discard", data_name="data",
                         label_name="softmax_label", seed=seed)


class CSVIter(NDArrayIter):
    """CSV reader (REF:src/io/iter_csv.cc): ``data_csv`` (+``label_csv``)
    reshaped to ``data_shape`` rows."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=128, round_batch=True, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32, ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32,
                               ndmin=2).reshape((-1,) + tuple(label_shape))
        else:
            label = np.zeros((len(data), 1), dtype=np.float32)
        super().__init__(data, label, batch_size=batch_size,
                         last_batch_handle="pad" if round_batch else "discard")


class LibSVMIter(DataIter):
    """LibSVM text reader (REF:src/io/iter_libsvm.cc): lines of
    ``label idx:val idx:val ...`` batched as CSR matrices; labels may
    themselves be sparse (``label_libsvm``)."""

    def __init__(self, data_libsvm, data_shape, batch_size,
                 label_libsvm=None, label_shape=(1,), round_batch=True,
                 **kwargs):
        super().__init__(batch_size)
        self._num_features = int(np.prod(data_shape))
        self._label_shape = tuple(label_shape)
        label_dim = int(np.prod(self._label_shape))
        self._rows, scalars = self._parse(data_libsvm)
        if label_libsvm:
            lab_rows, _ = self._parse(label_libsvm)
            if len(lab_rows) != len(self._rows):
                raise MXNetError("label_libsvm row count != data rows")
            self._labels = []
            for r in lab_rows:
                vec = np.zeros(label_dim, np.float32)
                for k, v in r:
                    vec[k] = v
                self._labels.append(vec)
        elif label_dim > 1:
            self._labels = []
            for s in scalars:
                vec = np.zeros(label_dim, np.float32)
                vec[0] = s
                self._labels.append(vec)
        else:
            self._labels = scalars
        self.round_batch = round_batch
        self.reset()

    @staticmethod
    def _parse(path):
        rows, labels = [], []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                rows.append([(int(k), float(v)) for k, v in
                             (p.split(":") for p in parts[1:])])
        return rows, labels

    def reset(self):
        self._cursor = 0
        self._pad = 0

    def state_dict(self):
        return {"iter": "LibSVMIter", "version": 1,
                "cursor": int(self._cursor)}

    def load_state_dict(self, state):
        _check_state(state, "LibSVMIter")
        self._cursor = int(state["cursor"])
        self._pad = 0

    def iter_next(self):
        n = len(self._rows)
        if self._cursor >= n:
            return False
        end = self._cursor + self.batch_size
        self._pad = max(0, end - n)
        if self._pad and not self.round_batch:
            return False
        sel = [(self._cursor + i) % n for i in range(self.batch_size)]
        self._cursor = min(end, n)
        data, indices, indptr = [], [], [0]
        labels = []
        for i in sel:
            for k, v in self._rows[i]:
                indices.append(k)
                data.append(v)
            indptr.append(len(data))
            labels.append(self._labels[i])
        from ..ndarray import sparse as _sparse
        self._data_batch = _sparse.csr_matrix(
            (np.asarray(data, np.float32), np.asarray(indices, np.int32),
             np.asarray(indptr, np.int32)),
            shape=(self.batch_size, self._num_features))
        lab = np.asarray(labels, np.float32)
        if lab.ndim > 1:
            lab = lab.reshape((self.batch_size,) + self._label_shape)
        self._label_batch = nd.array(lab)
        return True

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._num_features))]

    @property
    def provide_label(self):
        shp = (self.batch_size,) if int(np.prod(self._label_shape)) == 1 \
            else (self.batch_size,) + self._label_shape
        return [DataDesc("softmax_label", shp)]

    def getdata(self):
        return [self._data_batch]

    def getlabel(self):
        return [self._label_batch]

    def getpad(self):
        return self._pad


class ImageRecordIter(DataIter):
    """RecordIO image pipeline (REF:src/io/iter_image_recordio_2.cc):
    threaded JPEG decode + augmentation + NCHW batching, prefetched.

    Augmentations follow REF:src/io/image_aug_default.cc's core set:
    ``resize`` (shorter side), ``rand_crop``, ``rand_mirror``, center crop to
    ``data_shape``, mean/std normalization.  Decode fan-out uses a thread pool
    (``preprocess_threads``); when the native ``libtpumx_io`` extension is
    built it supplies the chunked record reader.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, path_imgidx=None,
                 label_width=1, shuffle=False, rand_crop=False,
                 rand_mirror=False, resize=-1, mean_r=0.0, mean_g=0.0,
                 mean_b=0.0, std_r=1.0, std_g=1.0, std_b=1.0,
                 preprocess_threads=4, prefetch_buffer=4, round_batch=True,
                 seed=0, use_native=None, output_dtype="float32",
                 output_layout="NCHW", **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        check(len(self.data_shape) == 3, "data_shape must be (C,H,W)")
        # TPU-feed variants (r4): output_dtype="uint8" skips host-side
        # normalization — the iterator then emits raw pixels and the
        # consumer normalizes ON DEVICE (DevicePrefetchIter(normalize=...))
        # so host + interconnect move 4x fewer bytes; output_layout="NHWC"
        # emits channels-last, the layout the TPU conv path wants.
        check(output_dtype in ("float32", "uint8"),
              "output_dtype must be float32|uint8")
        check(output_layout in ("NCHW", "NHWC"),
              "output_layout must be NCHW|NHWC")
        self.output_dtype = output_dtype
        self.output_layout = output_layout
        self.label_width = label_width
        self.shuffle = shuffle
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.resize = resize
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.std = np.array([std_r, std_g, std_b], np.float32)
        self.rng = np.random.RandomState(seed)
        self.round_batch = round_batch

        # native C++ pipeline (native/tpumx_io.cpp): threaded decode+augment
        # in one shared library — the hot path for training (SURVEY §3.5).
        # Python/cv2 path remains for PNG records and round_batch=False.
        self._native = None
        native_ok = (round_batch and self.data_shape[0] == 3 and
                     self._first_record_is_jpeg(path_imgrec))
        if use_native and not native_ok:
            raise MXNetError(
                "use_native=True requires JPEG records, round_batch=True and "
                "3-channel data_shape")
        if use_native is not False and native_ok:
            try:
                from ..lib.recordio_cpp import NativeImagePipe
                self._native = NativeImagePipe(
                    path_imgrec, batch_size=batch_size,
                    data_shape=self.data_shape, resize=resize,
                    rand_crop=rand_crop, rand_mirror=rand_mirror,
                    mean=self.mean, std=self.std,
                    preprocess_threads=preprocess_threads,
                    prefetch_buffer=prefetch_buffer, shuffle=shuffle,
                    seed=seed, label_width=label_width,
                    output_dtype=output_dtype, output_layout=output_layout)
            except Exception as e:
                if use_native:
                    raise
                import warnings
                warnings.warn(f"native io unavailable ({e}); "
                              "using the Python pipeline")
        if self._native is not None:
            n = len(self._native)
            self._nat_batches = (n + batch_size - 1) // batch_size
            self._nat_pad = self._nat_batches * batch_size - n
            self._nat_seen = 0
            self._pad = 0
            return

        import cv2  # decode backend, as in the reference (OpenCV)
        self._cv2 = cv2
        from ..recordio import MXRecordIO, MXIndexedRecordIO, unpack
        self._unpack = unpack
        if path_imgidx and os.path.isfile(path_imgidx):
            self._rec = MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
            self._order = list(self._rec.keys)
        else:
            # no index: scan once to record offsets, enabling shuffle anyway
            self._rec = MXRecordIO(path_imgrec, "r")
            self._offsets = []
            while True:
                pos = self._rec.tell()
                if self._rec.read() is None:
                    break
                self._offsets.append(pos)
            self._order = list(range(len(self._offsets)))
        self._pool = ThreadPoolExecutor(max_workers=preprocess_threads)
        self._prefetch = prefetch_buffer
        self.reset()

    @property
    def provide_data(self):
        c, h, w = self.data_shape
        shp = (h, w, c) if self.output_layout == "NHWC" else (c, h, w)
        dt = np.uint8 if self.output_dtype == "uint8" else np.float32
        return [DataDesc("data", (self.batch_size,) + shp, dtype=dt,
                         layout="N" + self.output_layout[1:])]

    @property
    def provide_label(self):
        shp = (self.batch_size,) if self.label_width == 1 else (
            self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shp)]

    @staticmethod
    def _first_record_is_jpeg(path, sample=8):
        """The native pipeline decodes JPEG only; peek the payload magic of
        the first few records (a mixed-format file beyond the sample still
        fails mid-epoch — use use_native=False for those)."""
        try:
            from ..recordio import MXRecordIO, unpack
            r = MXRecordIO(path, "r")
            seen = 0
            try:
                for _ in range(sample):
                    raw = r.read()
                    if raw is None:
                        break
                    _, payload = unpack(raw)
                    if bytes(payload[:2]) != b"\xff\xd8":
                        return False
                    seen += 1
            finally:
                r.close()
            return seen > 0
        except Exception:
            return False

    def reset(self):
        if self._native is not None:
            self._native.reset()
            self._nat_seen = 0
            self._pad = 0
            return
        if self.shuffle:
            self.rng.shuffle(self._order)
        self._cursor = 0
        self._pending = []

    def state_dict(self):
        """Epoch cursor + shuffle permutation + augmentation RNG state.
        Python pipeline only: the native C++ pipe keeps its cursors and
        per-thread RNGs internal — construct with ``use_native=False``
        when deterministic resume matters (docs/robustness.md)."""
        if self._native is not None:
            raise NotImplementedError(
                "ImageRecordIter: state_dict is unsupported on the native "
                "pipeline (internal decode-thread cursors) — pass "
                "use_native=False for deterministic resume")
        return {"iter": type(self).__name__, "version": 1,
                "cursor": int(self._cursor),
                "order": [int(i) for i in self._order],
                "rng": self.rng.get_state()}

    def load_state_dict(self, state):
        _check_state(state, type(self).__name__)
        if self._native is not None:
            raise NotImplementedError(
                "ImageRecordIter: load_state_dict is unsupported on the "
                "native pipeline — pass use_native=False")
        self._order = [int(i) for i in state["order"]]
        self._cursor = int(state["cursor"])
        self._pad = 0
        self._pending = []
        self.rng.set_state(_np_rng_tuple(state["rng"]))

    def close(self):
        """Shut down the decode pool and release the record reader."""
        if self._native is not None:
            return  # the native pipe owns its threads for its lifetime
        pool = getattr(self, "_pool", None)
        if pool is not None:
            pool.shutdown(wait=True)
        rec = getattr(self, "_rec", None)
        if rec is not None:
            try:
                rec.close()
            except Exception:  # already closed
                pass

    def _read_raw(self, key):
        from ..recordio import MXIndexedRecordIO
        if isinstance(self._rec, MXIndexedRecordIO):
            return self._rec.read_idx(key)
        self._rec.record.seek(self._offsets[key])
        return self._rec.read()

    def _decode_one(self, raw, aug):
        # `aug` = (crop_frac_y, crop_frac_x, mirror) drawn on the MAIN thread:
        # np.random.RandomState is not thread-safe, so pool workers must not
        # touch self.rng (and per-batch draws keep seeded runs reproducible
        # regardless of worker scheduling).
        cv2 = self._cv2
        fy, fx, mirror = aug
        header, img_bytes = self._unpack(raw)
        img = cv2.imdecode(np.frombuffer(img_bytes, np.uint8), cv2.IMREAD_COLOR)
        check(img is not None, "image decode failed")
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
        c, h, w = self.data_shape
        if self.resize > 0:
            short = min(img.shape[:2])
            scale = self.resize / short
            img = cv2.resize(img, (max(w, int(round(img.shape[1] * scale))),
                                   max(h, int(round(img.shape[0] * scale)))))
        ih, iw = img.shape[:2]
        if ih < h or iw < w:
            img = cv2.resize(img, (max(w, iw), max(h, ih)))
            ih, iw = img.shape[:2]
        if self.rand_crop:
            y = int(fy * (ih - h + 1))
            x = int(fx * (iw - w + 1))
        else:
            y, x = (ih - h) // 2, (iw - w) // 2
        img = img[y:y + h, x:x + w]
        if mirror:
            img = img[:, ::-1]
        if self.output_dtype != "uint8":  # u8: raw pixels, device normalizes
            img = (img.astype(np.float32) - self.mean) / self.std
        label = header.label if self.label_width > 1 else float(
            np.asarray(header.label).ravel()[0])
        if self.output_layout == "NCHW":
            img = img.transpose(2, 0, 1)
        return np.ascontiguousarray(img), label

    def iter_next(self):
        if self._native is not None:
            out = self._native.next_batch()
            if out is None:
                return False
            self._data, self._label = out
            self._nat_seen += 1
            self._pad = self._nat_pad if self._nat_seen == self._nat_batches \
                else 0
            return True
        n = len(self._order)
        if self._cursor >= n:
            return False
        idxs = [self._order[(self._cursor + i) % n]
                for i in range(self.batch_size)]
        self._pad = max(0, self._cursor + self.batch_size - n)
        if self._pad and not self.round_batch:
            return False
        self._cursor += self.batch_size
        raws = [self._read_raw(i) for i in idxs]  # sequential file reads
        augs = [(self.rng.rand(), self.rng.rand(),
                 self.rand_mirror and self.rng.rand() < 0.5)
                for _ in idxs]
        decoded = list(self._pool.map(self._decode_one, raws, augs))
        self._data = np.stack([d for d, _ in decoded])
        labels = [l for _, l in decoded]
        self._label = np.asarray(labels, dtype=np.float32)
        return True

    def getdata(self):
        return [nd.array(self._data)]

    def getlabel(self):
        return [nd.array(self._label)]

    def getpad(self):
        return self._pad


class ImageDetRecordIter(DataIter):
    """Detection RecordIO pipeline (REF:src/io/iter_image_det_recordio.cc +
    REF:src/io/image_det_aug_default.cc): threaded JPEG decode +
    box-aware augmentation (IoU-constrained random crop, flip with box
    transform, force-resize) + batching into (data (B,C,H,W),
    label (B, max_objects, 5)) — the SSD training input pair, with labels
    padded to the fixed width MultiBoxTarget wants on TPU.

    The hot path is the native C++ pipeline (native/tpumx_io.cpp
    DetPipe); ``use_native=False`` (or an unbuildable lib) falls back to
    the Python ``image.detection.ImageDetIter`` augmenters, which share
    the same label contract."""

    def __init__(self, path_imgrec, data_shape, batch_size, max_objects=None,
                 shuffle=False, rand_crop=0, rand_mirror=False,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                 std_b=1.0, min_object_covered=0.3, area_range=(0.3, 1.0),
                 aspect_ratio_range=(0.75, 1.33), max_attempts=20,
                 preprocess_threads=4, prefetch_buffer=4, seed=0,
                 use_native=None, output_dtype="float32",
                 output_layout="NCHW", **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        check(len(self.data_shape) == 3, "data_shape must be (C,H,W)")
        # same TPU-feed contract as ImageRecordIter (uint8 feed +
        # on-device normalization, NHWC emit); native-path only — the
        # Python det fallback keeps the classic f32/NCHW contract
        check(output_dtype in ("float32", "uint8"),
              "output_dtype must be float32|uint8")
        check(output_layout in ("NCHW", "NHWC"),
              "output_layout must be NCHW|NHWC")
        if (output_dtype != "float32" or output_layout != "NCHW") and \
                use_native is False:
            raise MXNetError("output_dtype/output_layout variants need "
                             "the native pipeline (use_native=False set)")
        self.output_dtype = output_dtype
        self.output_layout = output_layout
        self.max_objects = max_objects or self._scan_max_objects(path_imgrec)
        self._pad = 0
        self._native = None
        if use_native is not False:
            try:
                from ..lib.recordio_cpp import NativeDetPipe
                self._native = NativeDetPipe(
                    path_imgrec, batch_size=batch_size,
                    data_shape=self.data_shape,
                    max_objects=self.max_objects,
                    rand_crop=bool(rand_crop), rand_mirror=rand_mirror,
                    mean=(mean_r, mean_g, mean_b),
                    std=(std_r, std_g, std_b),
                    min_object_covered=min_object_covered,
                    area_range=area_range,
                    aspect_ratio_range=aspect_ratio_range,
                    max_attempts=max_attempts,
                    preprocess_threads=preprocess_threads,
                    prefetch_buffer=prefetch_buffer, shuffle=shuffle,
                    seed=seed, output_dtype=output_dtype,
                    output_layout=output_layout)
            except Exception as e:
                if use_native:
                    raise
                if output_dtype != "float32" or output_layout != "NCHW":
                    raise  # no Python analog of the TPU-feed contract
                import warnings
                warnings.warn(f"native det io unavailable ({e}); "
                              "using the Python pipeline")
        if self._native is not None:
            n = len(self._native)
            self._nat_batches = (n + batch_size - 1) // batch_size
            self._nat_pad = self._nat_batches * batch_size - n
            self._nat_seen = 0
            return
        # Python fallback: the image.detection iterator (same label layout)
        from ..image.detection import ImageDetIter
        mean = None
        std = None
        if mean_r or mean_g or mean_b:
            mean = np.array([mean_r, mean_g, mean_b], np.float32)
        if std_r != 1.0 or std_g != 1.0 or std_b != 1.0:
            std = np.array([std_r, std_g, std_b], np.float32)
        self._py = ImageDetIter(
            batch_size, self.data_shape, path_imgrec=path_imgrec,
            shuffle=shuffle, max_objects=self.max_objects,
            rand_crop=rand_crop, rand_mirror=rand_mirror, mean=mean,
            std=std, min_object_covered=min_object_covered,
            area_range=area_range, aspect_ratio_range=aspect_ratio_range,
            max_attempts=max_attempts, **kwargs)

    @staticmethod
    def _scan_max_objects(path_imgrec):
        """One header-only pass over the .rec (no image decode): widest
        label block, in boxes."""
        from ..recordio import MXRecordIO, unpack
        widest = 1
        r = MXRecordIO(path_imgrec, "r")
        try:
            while True:
                raw = r.read()
                if raw is None:
                    break
                header, _ = unpack(raw)
                if header.flag:
                    widest = max(widest, int(header.flag) // 5)
        finally:
            r.close()
        return widest

    @property
    def provide_data(self):
        c, h, w = self.data_shape
        shp = (h, w, c) if self.output_layout == "NHWC" else (c, h, w)
        dt = np.uint8 if self.output_dtype == "uint8" else np.float32
        return [DataDesc("data", (self.batch_size,) + shp, dtype=dt,
                         layout="N" + self.output_layout[1:])]

    @property
    def provide_label(self):
        return [DataDesc("label",
                         (self.batch_size, self.max_objects, 5))]

    def reset(self):
        if self._native is not None:
            self._native.reset()
            self._nat_seen = 0
            self._pad = 0
        else:
            self._py.reset()

    def iter_next(self):
        if self._native is not None:
            out = self._native.next_batch()
            if out is None:
                return False
            self._data, self._label = out
            self._nat_seen += 1
            self._pad = self._nat_pad if self._nat_seen == self._nat_batches \
                else 0
            return True
        try:
            batch = self._py.next()
        except StopIteration:
            return False
        self._data = batch.data[0].asnumpy()
        self._label = batch.label[0].asnumpy()
        self._pad = batch.pad or 0
        return True

    def getdata(self):
        return [nd.array(self._data)]

    def getlabel(self):
        return [nd.array(self._label)]

    def getpad(self):
        return self._pad
