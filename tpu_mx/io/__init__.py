"""Data iterators — the ``mx.io`` surface (REF:python/mxnet/io/io.py +
the C++ iterators of REF:src/io/).  See ``tpu_mx/io/io.py``."""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, DevicePrefetchIter, MNISTIter, CSVIter, ImageRecordIter,
                 ImageDetRecordIter, LibSVMIter)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "DevicePrefetchIter", "MNISTIter", "CSVIter", "ImageRecordIter",
           "ImageDetRecordIter", "LibSVMIter"]
