"""Test kit: the de-facto test framework of the reference
(REF:python/mxnet/test_utils.py), ported in spirit (SURVEY §4):

- `assert_almost_equal` with per-dtype default tolerances,
- `check_numeric_gradient` — finite differences vs the autograd tape
  (the FGradient oracle),
- `check_consistency` — run the same function on several contexts/dtypes and
  compare outputs & gradients (the cross-backend oracle; here TPU-vs-CPU),
- `default_context` override hook enabling the reference's context-override
  test-reuse pattern (tests/gpu re-running unittest files on another device).
"""
from __future__ import annotations

import numpy as np

from .random import host_rng as _host_rng
from . import autograd, context
from .ndarray import NDArray, array

_DEFAULT_CTX = [None]

_DTYPE_TOL = {
    np.dtype(np.float16): (1e-2, 1e-2),
    np.dtype(np.float32): (1e-4, 1e-5),
    np.dtype(np.float64): (1e-6, 1e-8),
}


def default_context():
    return _DEFAULT_CTX[0] or context.current_context()


def set_default_context(ctx):
    _DEFAULT_CTX[0] = ctx


def _as_np(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return np.asarray(a)


def default_tols(*arrays):
    rtol, atol = 1e-5, 1e-7
    for a in arrays:
        dt = np.dtype(_as_np(a).dtype)
        if dt in _DTYPE_TOL:
            r, at = _DTYPE_TOL[dt]
            rtol, atol = max(rtol, r), max(atol, at)
        elif str(dt) == "bfloat16":
            rtol, atol = max(rtol, 1e-2), max(atol, 1e-2)
    return rtol, atol


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    a_np, b_np = _as_np(a).astype(np.float64), _as_np(b).astype(np.float64)
    if rtol is None or atol is None:
        r, at = default_tols(a, b)
        rtol = rtol if rtol is not None else r
        atol = atol if atol is not None else at
    np.testing.assert_allclose(a_np, b_np, rtol=rtol, atol=atol,
                               err_msg=f"{names[0]} vs {names[1]}")


def almost_equal(a, b, rtol=None, atol=None):
    try:
        assert_almost_equal(a, b, rtol, atol)
        return True
    except AssertionError:
        return False


def same(a, b):
    return np.array_equal(_as_np(a), _as_np(b))


def rand_ndarray(shape, dtype="float32", ctx=None, low=-1.0, high=1.0):
    data = _host_rng().uniform(low, high, size=shape).astype(dtype)
    return array(data, ctx=ctx or default_context())


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-3):
    """Finite-difference check of tape gradients, like the reference's
    check_numeric_gradient (REF:python/mxnet/test_utils.py).

    fn: callable(list[NDArray]) -> scalar-reducible NDArray.
    inputs: list of numpy arrays (float64 recommended upstream; float32 here).
    """
    nds = [array(x.astype(np.float32)) for x in inputs]
    for a in nds:
        a.attach_grad()
    with autograd.record():
        out = fn(nds)
        loss = out.sum()
    loss.backward()
    analytic = [a.grad.asnumpy().copy() for a in nds]

    for idx, x in enumerate(inputs):
        numeric = np.zeros_like(x, dtype=np.float64)
        flat = x.reshape(-1)
        num_flat = numeric.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            lp = float(fn([array(v.astype(np.float32)) for v in inputs]).sum().asscalar())
            flat[j] = orig - eps
            lm = float(fn([array(v.astype(np.float32)) for v in inputs]).sum().asscalar())
            flat[j] = orig
            num_flat[j] = (lp - lm) / (2 * eps)
        np.testing.assert_allclose(analytic[idx], numeric, rtol=rtol, atol=atol,
                                   err_msg=f"gradient mismatch for input {idx}")


def check_consistency(fn, inputs, ctx_list=None, grad=True, rtol=None, atol=None):
    """Cross-backend oracle: run fn on each ctx, compare outputs and input
    gradients against the first ctx (reference: check_consistency running a
    symbol on [cpu, gpu, fp16-gpu] — here e.g. [cpu(0), tpu(0)])."""
    if ctx_list is None:
        ctx_list = [context.cpu(0)]
        if context.num_tpus():
            ctx_list.append(context.tpu(0))
    results = []
    for ctx in ctx_list:
        nds = [array(x, ctx=ctx) for x in inputs]
        if grad:
            for a in nds:
                a.attach_grad()
            with autograd.record():
                out = fn(nds)
                loss = out.sum()
            loss.backward()
            results.append((out.asnumpy(), [a.grad.asnumpy() for a in nds]))
        else:
            results.append((fn(nds).asnumpy(), []))
    ref_out, ref_grads = results[0]
    for (out, grads), ctx in zip(results[1:], ctx_list[1:]):
        assert_almost_equal(out, ref_out, rtol, atol, names=(str(ctx), str(ctx_list[0])))
        for g, rg in zip(grads, ref_grads):
            assert_almost_equal(g, rg, rtol, atol, names=(str(ctx), str(ctx_list[0])))
    return results


def assert_exception(fn, exception_type, *args, **kwargs):
    """REF test_utils.py:assert_exception."""
    try:
        fn(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError(
        f"{fn} did not raise {exception_type.__name__}")


def rand_shape_2d(dim0=10, dim1=10):
    return (_host_rng().randint(1, dim0 + 1), _host_rng().randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (_host_rng().randint(1, dim0 + 1), _host_rng().randint(1, dim1 + 1),
            _host_rng().randint(1, dim2 + 1))


def list_gpus():
    """REF test_utils.py:list_gpus — here: indices of TPU devices."""
    from . import context
    return list(range(context.num_tpus()))


def check_symbolic_forward(sym, inputs, expected, rtol=1e-5, atol=1e-20,
                           ctx=None):
    """REF test_utils.py:check_symbolic_forward: bind `sym` with `inputs`
    (list ordered like list_arguments) and compare outputs."""
    from . import cpu
    from .ndarray import array as nd_array
    ctx = ctx or cpu()
    args = sym.list_arguments()
    shapes = {a: np.asarray(x).shape for a, x in zip(args, inputs)}
    ex = sym.simple_bind(ctx, **shapes)
    for a, x in zip(args, inputs):
        ex.arg_dict[a][:] = np.asarray(x)
    outs = ex.forward()
    for out, exp in zip(outs, expected):
        np.testing.assert_allclose(out.asnumpy(), np.asarray(exp),
                                   rtol=rtol, atol=atol)
    return outs


def check_symbolic_backward(sym, inputs, out_grads, expected, rtol=1e-5,
                            atol=1e-20, ctx=None):
    """REF test_utils.py:check_symbolic_backward: forward+backward with
    given head gradients, compare input gradients (ordered like
    list_arguments)."""
    from . import cpu
    ctx = ctx or cpu()
    args = sym.list_arguments()
    shapes = {a: np.asarray(x).shape for a, x in zip(args, inputs)}
    ex = sym.simple_bind(ctx, grad_req="write", **shapes)
    for a, x in zip(args, inputs):
        ex.arg_dict[a][:] = np.asarray(x)
    ex.forward(is_train=True)
    ex.backward([array(np.asarray(g).astype(np.float32))
                 for g in out_grads])
    for a, exp in zip(args, expected):
        if exp is None:
            continue
        np.testing.assert_allclose(ex.grad_dict[a].asnumpy(),
                                   np.asarray(exp), rtol=rtol, atol=atol)
    return [ex.grad_dict[a] for a in args]
