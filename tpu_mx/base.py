"""Foundation utilities: registry, typed-parameter validation, logging.

TPU-native analog of the reference's dmlc-core foundation layer
(REF:3rdparty/dmlc-core — dmlc::Registry, dmlc::Parameter, logging).  Instead of
C++ reflection macros we use plain-Python descriptors; the *capability* kept is:
named registries with alias support, and declarative per-op/per-iterator
parameter structs with defaults, ranges and docs that surface in signatures.
"""
from __future__ import annotations

import logging
import numbers
import os

__all__ = ["Registry", "MXNetError", "check", "get_env", "dist_boot",
           "string_types", "numeric_types"]

logging.basicConfig(level=os.environ.get("TPU_MX_LOG_LEVEL", "INFO"))
logger = logging.getLogger("tpu_mx")

string_types = (str,)
numeric_types = (numbers.Number,)


class MXNetError(RuntimeError):
    """Framework-level error (name kept for API familiarity with the reference)."""


def check(cond, msg="check failed"):
    """dmlc CHECK() analog: raise MXNetError with message if cond is false."""
    if not cond:
        raise MXNetError(msg)


def get_env(name, default=None, dtype=str):
    """dmlc::GetEnv analog — typed environment variable lookup (SURVEY §5.6)."""
    val = os.environ.get(name)
    if val is None:
        return default
    if dtype is bool:
        return val.lower() in ("1", "true", "yes", "on")
    return dtype(val)


class Registry:
    """Named registry with alias support (dmlc::Registry analog).

    Used for optimizers, initializers, metrics, data iterators — every
    subsystem the reference exposes through string-keyed creation
    (e.g. ``mx.optimizer.create('sgd')``).
    """

    def __init__(self, name):
        self.name = name
        self._entries = {}

    def register(self, obj=None, *, name=None, aliases=()):
        def _do(o):
            key = (name or o.__name__).lower()
            self._entries[key] = o
            for a in aliases:
                self._entries[a.lower()] = o
            return o

        return _do(obj) if obj is not None else _do

    def get(self, key):
        k = key.lower()
        if k not in self._entries:
            raise KeyError(
                f"{self.name} registry has no entry '{key}'. "
                f"Known: {sorted(self._entries)}"
            )
        return self._entries[k]

    def create(self, key, *args, **kwargs):
        return self.get(key)(*args, **kwargs)

    def __contains__(self, key):
        return key.lower() in self._entries

    def keys(self):
        return sorted(self._entries)


def dist_boot():
    """Join the multi-process collective group from the launcher env
    (tools/launch.py: TPUMX_COORDINATOR / TPUMX_NUM_PROC / TPUMX_PROC_ID —
    the DMLC_PS_ROOT_URI analog).  Must run before any JAX computation.
    Returns True iff this process is part of a formed group."""
    import os
    coord = os.environ.get("TPUMX_COORDINATOR")
    if not coord:
        return False
    import jax
    try:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ["TPUMX_NUM_PROC"]),
            process_id=int(os.environ["TPUMX_PROC_ID"]))
        return True
    except RuntimeError:
        # already initialized (import-time boot) — verify membership
        return jax.process_count() == int(os.environ["TPUMX_NUM_PROC"])
