"""ctypes binding for the native data pipeline (native/tpumx_io.cpp).

The analog of the reference's Python→C crossing for its iterators
(REF:src/c_api — MXDataIterNext etc.), done with ctypes because pybind11
is not in the image.  All blocking calls release the GIL (ctypes does this
for foreign calls), so the C++ worker threads overlap with Python.
"""
from __future__ import annotations

import ctypes

import numpy as np

from . import ensure_built

__all__ = ["NativeImagePipe", "NativeDetPipe", "native_im2rec"]

_lib = None


def _load():
    global _lib
    if _lib is None:
        path = ensure_built()
        lib = ctypes.CDLL(path)
        lib.tmx_det_pipe_create_v2.restype = ctypes.c_void_p
        lib.tmx_det_pipe_create_v2.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int,
        ]
        lib.tmx_pipe_create_v2.restype = ctypes.c_void_p
        lib.tmx_pipe_create_v2.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.tmx_pipe_next.restype = ctypes.c_int
        lib.tmx_pipe_next.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_float)]
        lib.tmx_pipe_size.restype = ctypes.c_longlong
        lib.tmx_pipe_size.argtypes = [ctypes.c_void_p]
        lib.tmx_pipe_reset.restype = None
        lib.tmx_pipe_reset.argtypes = [ctypes.c_void_p]
        lib.tmx_pipe_error.restype = ctypes.c_char_p
        lib.tmx_pipe_error.argtypes = [ctypes.c_void_p]
        lib.tmx_pipe_destroy.restype = None
        lib.tmx_pipe_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class NativeImagePipe:
    """Threaded RecordIO→JPEG→augment→NCHW pipeline running in C++."""

    def __init__(self, path_imgrec, batch_size, data_shape, resize=-1,
                 rand_crop=False, rand_mirror=False, mean=(0.0, 0.0, 0.0),
                 std=(1.0, 1.0, 1.0), preprocess_threads=4,
                 prefetch_buffer=4, shuffle=False, seed=0, label_width=1,
                 output_dtype="float32", output_layout="NCHW"):
        if output_dtype not in ("float32", "uint8"):
            raise ValueError(f"output_dtype must be float32|uint8, "
                             f"got {output_dtype!r}")
        if output_layout not in ("NCHW", "NHWC"):
            raise ValueError(f"output_layout must be NCHW|NHWC, "
                             f"got {output_layout!r}")
        lib = _load()
        c, h, w = data_shape
        mean_arr = (ctypes.c_float * 3)(*[float(m) for m in mean])
        std_arr = (ctypes.c_float * 3)(*[float(s) for s in std])
        err = ctypes.create_string_buffer(1024)
        self._u8 = output_dtype == "uint8"
        self._nhwc = output_layout == "NHWC"
        self._h = lib.tmx_pipe_create_v2(
            path_imgrec.encode(), batch_size, c, h, w,
            int(resize), int(bool(rand_crop)), int(bool(rand_mirror)),
            mean_arr, std_arr, int(preprocess_threads), int(prefetch_buffer),
            int(bool(shuffle)), int(seed), int(label_width),
            int(self._u8), int(self._nhwc), err, len(err))
        if not self._h:
            raise IOError("NativeImagePipe: %s" %
                          err.value.decode(errors="replace"))
        self._lib = lib
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        # the shape next_batch actually emits (NHWC reorders data_shape)
        self.out_shape = (h, w, c) if self._nhwc else (c, h, w)
        self.out_dtype = np.uint8 if self._u8 else np.float32
        self.label_width = label_width
    def __len__(self):
        return int(self._lib.tmx_pipe_size(self._h))

    def next_batch(self):
        """Returns (data, label) fresh arrays, or None at epoch end.  The
        C++ side fills the arrays directly — one copy total."""
        data = np.empty((self.batch_size,) + self.out_shape, self.out_dtype)
        label = np.empty((self.batch_size, self.label_width), np.float32)
        n = self._lib.tmx_pipe_next(
            self._h,
            data.ctypes.data_as(ctypes.c_void_p),
            label.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if n < 0:
            raise IOError("NativeImagePipe: %s" %
                          self._lib.tmx_pipe_error(self._h).decode(
                              errors="replace"))
        if n == 0:
            return None
        return data, label[:, 0] if self.label_width == 1 else label

    def reset(self):
        self._lib.tmx_pipe_reset(self._h)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.tmx_pipe_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeDetPipe:
    """Threaded RecordIO→JPEG→det-augment→(NCHW, (max_objects,5)) pipeline
    in C++ (native/tpumx_io.cpp DetPipe — the
    REF:src/io/iter_image_det_recordio.cc analog).  Labels come back as
    the fixed-width padded box blocks MultiBoxTarget wants."""

    def __init__(self, path_imgrec, batch_size, data_shape, max_objects,
                 rand_crop=False, rand_mirror=False, mean=(0.0, 0.0, 0.0),
                 std=(1.0, 1.0, 1.0), min_object_covered=0.3,
                 area_range=(0.3, 1.0), aspect_ratio_range=(0.75, 1.33),
                 max_attempts=20, preprocess_threads=4, prefetch_buffer=4,
                 shuffle=False, seed=0, output_dtype="float32",
                 output_layout="NCHW"):
        if output_dtype not in ("float32", "uint8"):
            raise ValueError(f"output_dtype must be float32|uint8, "
                             f"got {output_dtype!r}")
        if output_layout not in ("NCHW", "NHWC"):
            raise ValueError(f"output_layout must be NCHW|NHWC, "
                             f"got {output_layout!r}")
        lib = _load()
        c, h, w = data_shape
        mean_arr = (ctypes.c_float * 3)(*[float(m) for m in mean])
        std_arr = (ctypes.c_float * 3)(*[float(s) for s in std])
        err = ctypes.create_string_buffer(1024)
        self._u8 = output_dtype == "uint8"
        self._nhwc = output_layout == "NHWC"
        self._h = lib.tmx_det_pipe_create_v2(
            path_imgrec.encode(), batch_size, c, h, w, int(max_objects),
            int(bool(rand_crop)), int(bool(rand_mirror)), mean_arr, std_arr,
            float(min_object_covered), float(area_range[0]),
            float(area_range[1]), float(aspect_ratio_range[0]),
            float(aspect_ratio_range[1]), int(max_attempts),
            int(preprocess_threads), int(prefetch_buffer),
            int(bool(shuffle)), int(seed), int(self._u8), int(self._nhwc),
            err, len(err))
        if not self._h:
            raise IOError("NativeDetPipe: %s" %
                          err.value.decode(errors="replace"))
        self._lib = lib
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.out_shape = (h, w, c) if self._nhwc else (c, h, w)
        self.out_dtype = np.uint8 if self._u8 else np.float32
        self.max_objects = int(max_objects)

    def __len__(self):
        return int(self._lib.tmx_pipe_size(self._h))

    def next_batch(self):
        data = np.empty((self.batch_size,) + self.out_shape, self.out_dtype)
        label = np.empty((self.batch_size, self.max_objects, 5), np.float32)
        n = self._lib.tmx_pipe_next(
            self._h,
            data.ctypes.data_as(ctypes.c_void_p),
            label.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if n < 0:
            raise IOError("NativeDetPipe: %s" %
                          self._lib.tmx_pipe_error(self._h).decode(
                              errors="replace"))
        if n == 0:
            return None
        return data, label

    def reset(self):
        self._lib.tmx_pipe_reset(self._h)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.tmx_pipe_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def native_im2rec(lst_path, root, out_prefix, resize=0, quality=95,
                  num_thread=4, upscale=False):
    """Parallel C++ dataset packer (native/tpumx_io.cpp tmx_im2rec, the
    REF:tools/im2rec.cc analog): .lst -> out_prefix.rec/.idx, byte-format-
    compatible with tools/im2rec.py and every reader here.  resize=0
    stores original bytes; resize>0 re-encodes with the shorter side at
    `resize` (decode→bilinear→libjpeg at `quality`; downscale-only unless
    upscale=True, matching pack()).  JPEG inputs only; unreadable records
    are skipped with a stderr note.  Returns the record count."""
    _load()
    if not hasattr(_lib, "_im2rec_ready"):
        _lib.tmx_im2rec.restype = ctypes.c_long
        _lib.tmx_im2rec.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int]
        _lib._im2rec_ready = True
    err = ctypes.create_string_buffer(1024)
    n = _lib.tmx_im2rec(str(lst_path).encode(), str(root).encode(),
                        str(out_prefix).encode(), int(resize), int(quality),
                        int(num_thread), int(bool(upscale)), err, len(err))
    if n < 0:
        raise RuntimeError(f"native im2rec failed: {err.value.decode()}")
    return int(n)
