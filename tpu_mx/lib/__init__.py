"""Native components of the framework (C++, built lazily with g++).

The reference ships its data pipeline and runtime as C++
(REF:src/io/**, REF:src/engine/**); here the compute/scheduling side is
XLA's job, but the host-side input pipeline is genuinely CPU-bound
(SURVEY §7.3 hard-part 5), so it is native too: ``native/tpumx_io.cpp``
is compiled on first use into ``libtpumx_io.so`` next to this package.
"""
from __future__ import annotations

import os
import subprocess

_LIB_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_LIB_DIR, os.pardir, os.pardir, "native", "tpumx_io.cpp")
_SO = os.path.join(_LIB_DIR, "libtpumx_io.so")


class NativeBuildError(RuntimeError):
    pass


def ensure_built():
    """Compile the native library if missing or stale; returns the .so path.
    The .so is never shipped (built with -march=native for THIS machine);
    an installed layout without the C++ source uses whatever .so is
    present."""
    src = os.path.abspath(_SRC)
    if not os.path.isfile(src):
        if os.path.isfile(_SO):
            return _SO
        raise NativeBuildError(f"native source not found: {src}")
    if os.path.isfile(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(src):
        return _SO
    # build to a per-pid temp path then rename: atomic for concurrent
    # data-parallel processes racing to build on one machine
    tmp = f"{_SO}.build.{os.getpid()}"
    cmd = ["g++", "-O3", "-march=native", "-funroll-loops", "-std=c++17",
           "-shared", "-fPIC", src, "-o", tmp, "-ljpeg", "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True,
                       timeout=300)
        os.replace(tmp, _SO)
    except FileNotFoundError as e:
        raise NativeBuildError(f"g++ not available: {e}") from e
    except subprocess.CalledProcessError as e:
        raise NativeBuildError(
            f"native build failed:\n{e.stderr[-4000:]}") from e
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return _SO
