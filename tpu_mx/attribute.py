"""mx.attribute — AttrScope (REF:python/mxnet/attribute.py).

`with mx.AttrScope(ctx_group="dev1", lr_mult="0.1"):` attaches the given
attributes to every Symbol node created inside the scope — the mechanism
behind the reference's `group2ctx` manual model parallelism (the TPU
analog consumes `__ctx_group__` via sharding rules instead of device
copies, but the annotation surface is identical).  Scopes nest; inner
values win."""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current_attrs"]

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


def current_attrs():
    """Merged attribute dict of the active scopes (inner wins)."""
    merged = {}
    for frame in _stack():
        merged.update(frame)
    return merged


class AttrScope:
    def __init__(self, **attrs):
        # the reference stores every attr value as a string and prefixes
        # user keys with __...__ at consumption time; keep values as given
        # but stringify for .attr() parity
        self._attrs = {k: str(v) for k, v in attrs.items()}

    def __enter__(self):
        _stack().append(self._attrs)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False
