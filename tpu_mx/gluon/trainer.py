"""Gluon Trainer (REF:python/mxnet/gluon/trainer.py).

Owns the optimizer + kvstore; `step()` = allreduce_grads + update, exactly the
reference's split.  On TPU the grad "allreduce" for the eager path is the
kvstore facade (in-process sum / documented-sync dist); the *performance* path
is `tpu_mx.parallel.compile_train_step`, where the same optimizer's functional
core and the psum are fused into one XLA program (SURVEY §3.2 hot loop).
"""
from __future__ import annotations

from .. import optimizer as opt_mod
from ..base import MXNetError
from ..kvstore import create as kv_create
from ..ndarray import NDArray

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, dict) or hasattr(params, "values"):
            params = list(params.values())
        self._params = [p for p in params if p.grad_req != "null"]
        self._all_params = list(params)
        optimizer_params = optimizer_params or {}
        self._optimizer = opt_mod.create(optimizer, **optimizer_params) \
            if isinstance(optimizer, str) else optimizer
        self._optimizer.param_dict = {i: p for i, p in enumerate(self._params)}
        self._states = [None] * len(self._params)
        self._states_inited = [False] * len(self._params)
        self._kvstore = kv_create(kvstore) if isinstance(kvstore, str) and kvstore \
            else kvstore
        self._compression_params = compression_params
        if compression_params and self._kvstore:
            self._kvstore.set_gradient_compression(compression_params)
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False
        self._scale = 1.0

    @property
    def learning_rate(self):
        if self._optimizer.lr_scheduler:
            return self._optimizer.lr_scheduler(self._optimizer.num_update)
        return self._optimizer.lr

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _init_kvstore(self):
        if self._kvstore:
            for i, p in enumerate(self._params):
                self._kvstore.init(i, p.data())
        self._kv_initialized = True

    def _check_grads(self):
        for p in self._params:
            if p._data is None:
                raise MXNetError(
                    f"Parameter {p.name} is not initialized; call initialize() "
                    "and run a forward pass before step()")

    def step(self, batch_size, ignore_stale_grad=False):
        """grad-rescale by 1/batch_size, allreduce, apply update."""
        self._check_grads()
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self.allreduce_grads()
        self.update(batch_size, ignore_stale_grad)

    def allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, p in enumerate(self._params):
            self._kvstore.push(i, p.grad, priority=-i)
            self._kvstore.pull(i, p.grad, priority=-i)

    def update(self, batch_size, ignore_stale_grad=False):
        for i, p in enumerate(self._params):
            if not self._states_inited[i]:
                self._states[i] = \
                    self._optimizer.create_state_multi_precision(i, p.data())
                self._states_inited[i] = True
            self._states[i] = self._optimizer.update_multi_precision(
                i, p.data(), p.grad, self._states[i])

    def save_states(self, fname):
        """Optimizer + update-count state (REF trainer.save_states)."""
        import pickle
        import numpy as np
        import jax
        payload = {
            "states": jax.tree_util.tree_map(np.asarray, self._states),
            "states_inited": self._states_inited,
            "num_update": self._optimizer.num_update,
            "index_update_count": self._optimizer._index_update_count,
        }
        with open(fname, "wb") as f:
            pickle.dump(payload, f)

    def load_states(self, fname):
        import pickle
        import jax.numpy as jnp
        with open(fname, "rb") as f:
            payload = pickle.load(f)
        import jax
        self._states = jax.tree_util.tree_map(jnp.asarray, payload["states"])
        self._states_inited = payload["states_inited"]
        self._optimizer.num_update = payload["num_update"]
        self._optimizer._index_update_count = payload["index_update_count"]
