"""Gluon Trainer (REF:python/mxnet/gluon/trainer.py).

Owns the optimizer + kvstore; `step()` = allreduce_grads + update, exactly the
reference's split.  On TPU the grad "allreduce" for the eager path is the
kvstore facade (in-process sum / documented-sync dist); the *performance* path
is `tpu_mx.parallel.compile_train_step`, where the same optimizer's functional
core and the psum are fused into one XLA program (SURVEY §3.2 hot loop).
"""
from __future__ import annotations

from .. import optimizer as opt_mod
from ..base import MXNetError
from ..kvstore import create as kv_create
from ..ndarray import NDArray

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None,
                 fuse_update=True):
        if isinstance(params, dict) or hasattr(params, "values"):
            params = list(params.values())
        self._params = [p for p in params if p.grad_req != "null"]
        self._all_params = list(params)
        optimizer_params = optimizer_params or {}
        self._optimizer = opt_mod.create(optimizer, **optimizer_params) \
            if isinstance(optimizer, str) else optimizer
        self._optimizer.param_dict = {i: p for i, p in enumerate(self._params)}
        self._states = [None] * len(self._params)
        self._states_inited = [False] * len(self._params)
        self._kvstore = kv_create(kvstore) if isinstance(kvstore, str) and kvstore \
            else kvstore
        self._compression_params = compression_params
        if compression_params and self._kvstore:
            self._kvstore.set_gradient_compression(compression_params)
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False
        self._scale = 1.0
        # fused update: ONE XLA program applies the optimizer to every
        # parameter (the reference's aggregated multi_sgd/multi_mp_sgd
        # kernels, REF:src/operator/optimizer_op.cc) instead of one
        # dispatch per parameter
        self._fuse_update = fuse_update
        self._fused_cache = {}

    @property
    def learning_rate(self):
        if self._optimizer.lr_scheduler:
            return self._optimizer.lr_scheduler(self._optimizer.num_update)
        return self._optimizer.lr

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _init_kvstore(self):
        if self._kvstore:
            for i, p in enumerate(self._params):
                self._kvstore.init(i, p.data())
        self._kv_initialized = True

    def _check_grads(self):
        for p in self._params:
            if p._data is None:
                raise MXNetError(
                    f"Parameter {p.name} is not initialized; call initialize() "
                    "and run a forward pass before step()")

    def step(self, batch_size, ignore_stale_grad=False):
        """grad-rescale by 1/batch_size, allreduce, apply update."""
        self._check_grads()
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self.allreduce_grads()
        self.update(batch_size, ignore_stale_grad)

    def allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, p in enumerate(self._params):
            self._kvstore.push(i, p.grad, priority=-i)
            self._kvstore.pull(i, p.grad, priority=-i)

    def update(self, batch_size, ignore_stale_grad=False):
        for i, p in enumerate(self._params):
            if not self._states_inited[i]:
                self._states[i] = \
                    self._optimizer.create_state_multi_precision(i, p.data())
                self._states_inited[i] = True
        opt_cls = type(self._optimizer)
        has_pure_core = opt_cls.update_core is not \
            opt_mod.Optimizer.update_core and \
            opt_cls.update_multi_precision is \
            opt_mod.Optimizer.update_multi_precision and \
            opt_cls.update is opt_mod.Optimizer.update
        if self._fuse_update and has_pure_core and len(self._params) > 1:
            return self._update_fused()
        for i, p in enumerate(self._params):
            self._states[i] = self._optimizer.update_multi_precision(
                i, p.data(), p.grad, self._states[i])

    def _update_fused(self):
        import jax
        import jax.numpy as jnp
        opt = self._optimizer
        n = len(self._params)
        for i in range(n):
            opt._update_count(i)
        lrs = jnp.asarray([opt._get_lr(i) for i in range(n)], jnp.float32)
        wds = jnp.asarray([opt._get_wd(i) for i in range(n)], jnp.float32)
        ts = jnp.asarray([opt._index_update_count[i] for i in range(n)],
                         jnp.float32)
        weights = [p.data()._data for p in self._params]
        grads = [p.grad._data for p in self._params]
        # static per-param facts baked into the trace; rescale/clip are read
        # from the optimizer at trace time, so they key the cache
        mp = [opt.multi_precision and w.dtype in (jnp.float16, jnp.bfloat16)
              for w in weights]
        key = (id(opt), opt.rescale_grad, opt.clip_gradient, tuple(mp),
               tuple(w.shape for w in weights))
        fn = self._fused_cache.get(key)
        if fn is None:
            def step_all(weights, grads, states, lrs, wds, ts):
                new_w, new_s = [], []
                for i in range(n):
                    if mp[i]:
                        master, inner = states[i]
                        nm, ni = opt.update_core(
                            master, grads[i].astype(jnp.float32), inner,
                            lrs[i], wds[i], ts[i])
                        new_w.append(nm.astype(weights[i].dtype))
                        new_s.append((nm, ni))
                    else:
                        nw, ns = opt.update_core(
                            weights[i], grads[i], states[i],
                            lrs[i], wds[i], ts[i])
                        new_w.append(nw.astype(weights[i].dtype))
                        new_s.append(ns)
                return new_w, new_s
            fn = jax.jit(step_all)
            self._fused_cache[key] = fn
        new_weights, self._states = fn(weights, grads, self._states,
                                       lrs, wds, ts)
        for p, w in zip(self._params, new_weights):
            p.data()._rebind(w)

    def save_states(self, fname):
        """Optimizer + update-count state (REF trainer.save_states)."""
        import pickle
        import numpy as np
        import jax
        payload = {
            "states": jax.tree_util.tree_map(np.asarray, self._states),
            "states_inited": self._states_inited,
            "num_update": self._optimizer.num_update,
            "index_update_count": self._optimizer._index_update_count,
        }
        from ..checkpoint import atomic_write
        with atomic_write(fname) as f:
            f.write(pickle.dumps(payload))

    def load_states(self, fname):
        import pickle
        import jax.numpy as jnp
        with open(fname, "rb") as f:
            payload = pickle.load(f)
        import jax
        self._states = jax.tree_util.tree_map(jnp.asarray, payload["states"])
        self._states_inited = payload["states_inited"]
        self._optimizer.num_update = payload["num_update"]
        self._optimizer._index_update_count = payload["index_update_count"]
