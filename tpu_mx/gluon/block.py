"""Gluon Block / HybridBlock (REF:python/mxnet/gluon/block.py).

Capabilities kept: define-by-run `Block`, `HybridBlock.hybridize()` graph
capture, deferred shape init, parameter collection/scoping, save/load,
`export()`.  TPU-native design (SURVEY §7.1): hybridize wraps the block's
*functionalized* forward in `jax.jit` — parameters enter as a traced pytree
(via the Parameter substitution scope), RNG enters as an explicit key, and
BatchNorm-style aux mutations leave as an updates pytree (`has_aux` vjp).
That replaces the reference's CachedOp + NNVM passes + static memory planning:
XLA does the fusion/planning; buffer donation plays the role of
`static_alloc`.
"""
from __future__ import annotations

import itertools
import re
import threading

import jax
import jax.numpy as jnp
import numpy as np

from .. import autograd
from .. import random as _random
from ..base import MXNetError
from ..context import current_context
from ..ndarray import NDArray, array
from ..ndarray import ops as F
from .parameter import Parameter, ParameterDict, param_substitution

__all__ = ["Block", "HybridBlock", "SymbolBlock", "nn"]

_NAME_COUNTER = {}
_NAME_LOCK = threading.Lock()


def _gen_prefix(hint):
    with _NAME_LOCK:
        idx = _NAME_COUNTER.get(hint, 0)
        _NAME_COUNTER[hint] = idx + 1
    return f"{hint}{idx}_"


class _BlockScope:
    """Placeholder for reference name_scope() compatibility."""

    def __init__(self, block):
        self._block = block

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class HookHandle:
    """Removable handle for a registered hook (reference: gluon.utils
    HookHandle)."""

    def __init__(self, hooks_list, hook):
        self._hooks_list = hooks_list
        self._hook = hook

    def detach(self):
        if self._hooks_list is not None and self._hook in self._hooks_list:
            self._hooks_list.remove(self._hook)
        self._hooks_list = None

    remove = detach

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.detach()


class Block:
    """Define-by-run module. Subclasses implement `forward(self, *args)`."""

    def __init__(self, prefix=None, params=None):
        hint = re.sub(r"(?<!^)(?=[A-Z])", "", type(self).__name__).lower()
        self._prefix = prefix if prefix is not None else _gen_prefix(hint)
        self._params = ParameterDict(self._prefix, shared=params)
        self._children = {}
        self._reg_params = {}
        self._scope = _BlockScope(self)

    # -- attribute registration ----------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            self.__dict__.setdefault("_children", {})[name] = value
        elif isinstance(value, Parameter):
            self.__dict__.setdefault("_reg_params", {})[name] = value
        super().__setattr__(name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._prefix.rstrip("_")

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """All params of self + descendants as one ParameterDict (full names)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(dict(self._params.items()))
        else:
            pat = re.compile(select)
            ret.update({k: v for k, v in self._params.items() if pat.match(k)})
        for child in self._children.values():
            ret.update(dict(child.collect_params(select).items()))
        return ret

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        hooks = self.__dict__.setdefault("_fwd_hooks", [])
        hooks.append(hook)
        return HookHandle(hooks, hook)

    def register_forward_pre_hook(self, hook):
        hooks = self.__dict__.setdefault("_fwd_pre_hooks", [])
        hooks.append(hook)
        return HookHandle(hooks, hook)

    def apply_fn(self, fn):
        """Reference Block.apply: run fn on self and all children."""
        for child in self._children.values():
            child.apply_fn(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        self.collect_params().initialize(init=init, ctx=ctx,
                                         force_reinit=force_reinit)
        return self

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self._params.values():
            p.cast(dtype)

    # -- save / load (attribute-path naming, reference save_parameters) ------
    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + n: p for n, p in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename):
        params = self._collect_params_with_prefix()
        payload = {k: p.data() for k, p in params.items() if p._data is not None}
        from ..ndarray import save as nd_save
        nd_save(filename, payload)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False):
        from ..ndarray import load as nd_load
        loaded = nd_load(filename)
        params = self._collect_params_with_prefix()
        for k, p in params.items():
            if k in loaded:
                p.set_data(loaded[k])
            elif not allow_missing:
                raise MXNetError(f"Parameter {k} missing in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(params)
            if extra:
                raise MXNetError(f"Extra params in file: {sorted(extra)}")

    save_params = save_parameters
    load_params = load_parameters

    # -- call ----------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        for hook in self.__dict__.get("_fwd_pre_hooks", ()):
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self.__dict__.get("_fwd_hooks", ()):
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        out = self(*inputs)
        lines = [f"{type(self).__name__}: params="
                 f"{sum(int(np.prod(p.shape)) for p in self.collect_params().values() if p.shape)}"]
        return "\n".join(lines)

    def __repr__(self):
        s = f"{type(self).__name__}(\n"
        for name, child in self._children.items():
            child_repr = repr(child).replace("\n", "\n  ")
            s += f"  ({name}): {child_repr}\n"
        return s + ")"


class HybridBlock(Block):
    """Block whose forward is functionally traceable → `hybridize()` compiles
    it with XLA (the CachedOp analog, REF:src/imperative/cached_op.cc)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._flags = {}
        self._cached_fns = {}          # (train, arg_struct) -> jitted fn
        self._param_order = None
        self._last_input_avals = None  # recorded for export()
        self._remat = False
        self._remat_policy = None

    def remat(self, active=True, policy=None):
        """Gradient rematerialization for this block's forward segment.

        When this block runs inside an enclosing compiled trace (a
        hybridized parent or `CompiledTrainStep`), its forward is wrapped
        in `jax.checkpoint`: activations inside the segment are recomputed
        during backward instead of stored, trading ~1 extra forward of
        FLOPs for the segment's activation HBM (SURVEY §7.1 — the TPU
        answer to big-batch training; no reference analog, MXNet 1.x
        mirrored memory via `mirror_stage` graph attrs).  Mark the
        repeated unit (e.g. each transformer layer), not the whole model.
        `policy` is forwarded to `jax.checkpoint` (a
        `jax.checkpoint_policies` entry) to keep select intermediates.
        Eager (non-traced) execution ignores the flag.  Returns self."""
        self._remat = bool(active)
        self._remat_policy = policy
        return self

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  inline_limit=None, **kwargs):
        self._active = active
        self._flags = dict(static_alloc=static_alloc, static_shape=static_shape)
        self._cached_fns = {}
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def infer_shape(self, *args):
        """Finalize deferred-init parameter shapes from example inputs
        (REF:python/mxnet/gluon/block.py HybridBlock.infer_shape).

        Leaf layers override this with closed-form rules (Dense, Conv,
        RNN cells, …).  The base implementation covers the two remaining
        cases:

        - a container whose CHILDREN hold the deferred params: one
          predict-mode forward over the example inputs finalizes every
          child (TPU-native divergence: the reference runs symbolic
          inference over the NNVM graph; here the eager forward IS the
          shape-inference pass — each layer's own infer_shape fires as
          the data reaches it);
        - a custom block with its OWN deferred params and no override:
          an explicit error (arbitrary Python forwards have no
          closed-form shape rule; the silent no-op this used to be
          surfaced later as a confusing uninitialized-parameter error).
        """
        own_incomplete = [p.name for p in self._reg_params.values()
                          if p._data is None and p._shape_incomplete()]
        if own_incomplete:
            raise MXNetError(
                f"{type(self).__name__} has deferred-shape parameters "
                f"{own_incomplete} but no infer_shape override; declare "
                "full shapes (in_units/in_channels/...) or override "
                "infer_shape(self, *args) with the block's shape rule")
        with autograd.predict_mode():
            self.forward(*args)

    def _uninitialized(self):
        return [p for p in self.collect_params().values() if p._data is None]

    def finalize_shapes(self, *args):
        """Finalize any deferred-shape parameters with ONE predict-mode
        forward over example inputs — and no-op (no device work) when the
        model declares every dim.  The public cold-start helper for
        benches/tools: `net.finalize_shapes(tiny_batch)` replaces the
        unconditional eager forward that cost an extra compile+transfer
        round-trip per model build over the tunneled TPU.  Returns self."""
        if self._uninitialized():
            with autograd.predict_mode():
                self(*args)
        return self

    # -- the functional core --------------------------------------------------
    def _functional_call(self, param_map, key, train, raw_args):
        """Pure: (params, key, *inputs) -> (outputs, aux_updates)."""
        scope = autograd.train_mode() if train else autograd.predict_mode()
        with param_substitution(param_map) as updates, \
                _random.key_scope(key), scope:
            out = self.forward(*raw_args)
        return out, updates

    def _remat_segment(self, args, kwargs):
        """Run this block's forward as a `jax.checkpoint` segment inside
        the enclosing functional trace (see `remat()`).  The segment is a
        pure function of (own params, rng key, positional array args);
        None/scalar args, kwargs, and any unused outer-scope values ride
        in the closure (jax.checkpoint differentiates closed-over tracers
        correctly — they just stay checkpoint residuals).  Aux updates
        (BatchNorm stats) recorded inside the segment are merged into the
        enclosing updates dict so they still reach the caller."""
        from .parameter import _active_substitution
        mapping, outer_updates = _active_substitution()
        own = {k: mapping[k] for k in self.collect_params() if k in mapping}
        key = _random.take_key()
        arr_idx = [i for i, a in enumerate(args)
                   if isinstance(a, (NDArray, jnp.ndarray, np.ndarray))]
        arrs = [args[i]._data if isinstance(args[i], NDArray) else args[i]
                for i in arr_idx]

        def seg(own_map, key, *arrs):
            m = dict(mapping)
            m.update(own_map)
            full = list(args)
            for i, a in zip(arr_idx, arrs):
                full[i] = a
            with param_substitution(m) as upd, _random.key_scope(key):
                out = Block.__call__(self, *full, **kwargs)
            return out, upd

        out, upd = jax.checkpoint(seg, policy=self._remat_policy)(
            own, key, *arrs)
        outer_updates.update(upd)
        return out

    def _ensure_cached(self, train):
        if train not in self._cached_fns:
            def pure_fn(param_map, key, *raw_args):
                return self._functional_call(param_map, key, train, raw_args)

            self._cached_fns[train] = jax.jit(pure_fn)
        return self._cached_fns[train]

    def __call__(self, *args, **kwargs):
        from .parameter import _active_substitution
        if _active_substitution() is None and not kwargs and args and \
                all(isinstance(a, (NDArray, jnp.ndarray, np.ndarray))
                    for a in args):
            # remember concrete input shapes for export() (works even if the
            # call below takes the eager path)
            self._last_input_avals = [
                jax.ShapeDtypeStruct(jnp.shape(a), jnp.result_type(a))
                for a in args]
        inside = _active_substitution() is not None
        if inside and self._remat and not self._uninitialized():
            return self._remat_segment(args, kwargs)
        if not self._active or inside:
            # plain path: not hybridized, OR already inside an enclosing
            # block's functional trace (children trace inline — one compiled
            # graph per outermost hybridized block, like CachedOp inlining)
            return super().__call__(*args, **kwargs)
        if self._uninitialized() or kwargs:
            # first call: eager to resolve deferred shapes (reference: the
            # first hybrid call performs the trace/shape-inference).
            # kwargs also take the eager path — they aren't part of the
            # cached-signature key, so compiling with them would silently
            # bake in defaults
            return super().__call__(*args, **kwargs)
        return self._call_cached(*args)

    def _call_cached(self, *args):
        params = {k: v for k, v in self.collect_params().items()
                  if v._data is not None}
        param_map = {k: p.data()._data for k, p in params.items()}
        raw_args = [a._data if isinstance(a, NDArray) else a for a in args]
        train = autograd.is_training() or autograd.is_recording()
        fn = self._ensure_cached(train)
        key = _random.take_key()

        nd_args = [a for a in args if isinstance(a, NDArray)]
        diff_params = {k: p for k, p in params.items()
                       if p.grad_req != "null" and
                       jnp.issubdtype(p.data().dtype, jnp.floating)}
        record = autograd._needs_tape(
            [p.data() for p in diff_params.values()] + nd_args)

        if record:
            const_map = {k: param_map[k] for k in param_map if k not in diff_params}
            diff_keys = list(diff_params)
            diff_arg_idx = [i for i, a in enumerate(args)
                            if isinstance(a, NDArray)
                            and jnp.issubdtype(a.dtype, jnp.floating)]

            def closed(diff_vals, *diff_raw):
                pm = dict(const_map)
                pm.update(dict(zip(diff_keys, diff_vals)))
                full = list(raw_args)
                for i, d in zip(diff_arg_idx, diff_raw):
                    full[i] = d
                return fn(pm, key, *full)

            out, vjp_fn, updates = jax.vjp(
                closed, [param_map[k] for k in diff_keys],
                *[raw_args[i] for i in diff_arg_idx], has_aux=True)

            multi = isinstance(out, (tuple, list))
            outs_raw = list(out) if multi else [out]
            outs = [NDArray(o) for o in outs_raw]
            tape_inputs = [diff_params[k].data() for k in diff_keys] + \
                          [args[i] for i in diff_arg_idx]

            def wrapped_vjp(out_ct):
                # rebuild the structure `closed` returned: backward() hands a
                # bare array for single-output nodes, a tuple otherwise
                cts = out_ct if isinstance(out_ct, tuple) else (out_ct,)
                in_cts = vjp_fn(list(cts) if multi else cts[0])
                param_cts, arg_cts = in_cts[0], in_cts[1:]
                return tuple(param_cts) + tuple(arg_cts)

            autograd._record_op(wrapped_vjp, tape_inputs, outs,
                                name=f"CachedOp[{self.name}]")
            result = outs if multi else outs[0]
        else:
            out, updates = fn(param_map, key, *raw_args)
            if isinstance(out, (tuple, list)):
                result = [NDArray(o) for o in out]
            else:
                result = NDArray(out)

        # apply aux mutations (BatchNorm running stats) post-hoc
        all_params = dict(params)
        for name, val in updates.items():
            if name in all_params:
                all_params[name]._data._rebind(val)
        return result

    # -- imperative face ------------------------------------------------------
    def forward(self, *args, **kwargs):
        kwparams = {}
        for name, p in self._reg_params.items():
            if p._data is None and p._shape_incomplete():
                self.infer_shape(*args)
            if p._data is None and not p._shape_incomplete():
                if p._deferred_init_args is None:
                    raise MXNetError(
                        f"Parameter {p.name} has not been initialized. Call "
                        ".initialize() on the block before the first forward "
                        "pass (reference semantics)")
                p._finish_deferred_init(p.shape)
        for name, p in self._reg_params.items():
            kwparams[name] = p.data()
        return self.hybrid_forward(F, *args, **kwparams, **kwargs)

    def hybrid_forward(self, F, *args, **kwargs):
        raise NotImplementedError

    def export(self, path, epoch=0, example_inputs=None):
        """Serialize the compiled inference graph + params
        (REF:python/mxnet/gluon/block.py export — symbol JSON + params file).

        TPU-native artifact set:
          ``{path}-symbol.json``          manifest (format, input specs)
          ``{path}-{epoch:04d}.params.npz``  parameters
          ``{path}-{epoch:04d}.stablehlo``   serialized `jax.export` program

        The StableHLO program is the inference (predict-mode) forward with
        static input shapes.  Shapes come from ``example_inputs`` or, if
        omitted, from the most recent call to this block.  Load it back with
        `SymbolBlock.imports` — forward results are bit-identical to the
        exporting block's.
        """
        import json

        import numpy as _np
        from jax import export as jexport

        params = self._collect_params_with_prefix()
        payload = {k: p.data() for k, p in params.items() if p._data is not None}
        from ..ndarray import save as nd_save
        nd_save(f"{path}-{epoch:04d}.params.npz", payload)

        if example_inputs is not None:
            in_avals = [
                jax.ShapeDtypeStruct(a.shape, a.dtype) for a in example_inputs]
        elif self._last_input_avals is not None:
            in_avals = self._last_input_avals
        else:
            raise MXNetError(
                "export() needs input shapes: call the block once (after "
                "hybridize()) or pass example_inputs=")

        # exported signature: (params_by_prefixed_name, key, *inputs);
        # prefixed names match the .params.npz keys so a loader needs no
        # other name mapping
        global_of = {k: p.name for k, p in params.items()
                     if p._data is not None}

        def infer_fn(pmap, key, *inputs):
            gmap = {global_of[k]: v for k, v in pmap.items()}
            out, _updates = self._functional_call(gmap, key, False, inputs)
            return out

        key0 = _random.take_key()
        param_avals = {k: jax.ShapeDtypeStruct(p.data().shape, p.data().dtype)
                       for k, p in params.items() if p._data is not None}
        exported = jexport.export(jax.jit(infer_fn))(
            param_avals, jax.ShapeDtypeStruct(key0.shape, key0.dtype),
            *in_avals)
        from ..checkpoint import atomic_write, write_manifest
        hlo_path = f"{path}-{epoch:04d}.stablehlo"
        with atomic_write(hlo_path) as f:
            f.write(exported.serialize())

        with atomic_write(f"{path}-symbol.json", "w") as f:
            f.write(json.dumps({
                "format": "tpu_mx-stablehlo-v1",
                "name": self.name,
                "params": sorted(payload),
                "inputs": [{"shape": list(a.shape),
                            "dtype": _np.dtype(a.dtype).name}
                           for a in in_avals],
                "artifact": f"{path.split('/')[-1]}-{epoch:04d}.stablehlo",
            }))
        # export is a checkpoint too: commit a manifest over the per-epoch
        # artifacts so a torn export can't be mistaken for a loadable
        # model.  {path}-symbol.json is deliberately NOT listed: it is
        # rewritten by every export with an epoch-dependent "artifact"
        # pointer, so digesting it would mark every OLDER epoch corrupt
        # the moment a newer one is exported
        write_manifest(path, epoch, [f"{path}-{epoch:04d}.params.npz",
                                     hlo_path])

    def optimize_for(self, *args, **kwargs):
        self.hybridize(True)


class SymbolBlock(HybridBlock):
    """Reference SymbolBlock wraps a saved symbol; here a saved compiled
    program (REF:python/mxnet/gluon/block.py SymbolBlock).  Build one from
    an `export()` artifact with `SymbolBlock.imports`."""

    def __init__(self, fn, params=None, prefix=None):
        super().__init__(prefix=prefix)
        self._fn = fn

    def hybrid_forward(self, F, *args, **params):
        return self._fn(*args)

    @staticmethod
    def imports(symbol_file, input_names=None, param_file=None, ctx=None):
        """Load an `export()`ed model: returns a callable block whose forward
        runs the deserialized StableHLO program (bit-identical to the
        exporter's inference forward).  Mirrors the reference's
        SymbolBlock.imports(symbol_file, input_names, param_file)."""
        import json
        import os

        import numpy as _np
        from jax import export as jexport

        with open(symbol_file) as f:
            manifest = json.load(f)
        if manifest.get("format") != "tpu_mx-stablehlo-v1":
            raise MXNetError(f"unsupported export format in {symbol_file}")
        art = os.path.join(os.path.dirname(symbol_file) or ".",
                           manifest["artifact"])
        with open(art, "rb") as f:
            exported = jexport.deserialize(f.read())
        from ..ndarray import load as nd_load
        if param_file is None:
            raise MXNetError("param_file is required")
        payload = {k: v._data for k, v in nd_load(param_file).items()}
        key0 = _random.take_key()

        def fn(*inputs):
            raw = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
                   for a in inputs]
            out = exported.call(payload, key0, *raw)
            if isinstance(out, (tuple, list)):
                return [NDArray(o) for o in out]
            return NDArray(out)

        blk = SymbolBlock(fn)
        blk._export_manifest = manifest
        return blk
