"""mx.gluon — the imperative/hybrid module system
(REF:python/mxnet/gluon/__init__.py)."""
from .parameter import Parameter, Constant, ParameterDict
from .block import Block, HybridBlock, SymbolBlock
from . import nn
from . import loss
from .trainer import Trainer
from . import rnn
from . import model_zoo
from . import utils
from . import contrib
from .utils import split_and_load
