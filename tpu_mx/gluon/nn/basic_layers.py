"""Gluon basic layers (REF:python/mxnet/gluon/nn/basic_layers.py)."""
from __future__ import annotations

import os

import numpy as np

from ... import autograd
from ... import layout as _layout_mod
from ..block import Block, HybridBlock

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm", "GroupNorm", "ReflectionPad2D",
           "LayerNorm", "InstanceNorm", "Embedding", "Flatten", "Activation",
           "LeakyReLU", "PReLU", "ELU", "SELU", "GELU", "Swish", "Lambda",
           "HybridLambda"]


class Sequential(Block):
    """Stack of blocks run sequentially."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        items = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*items[key])
            return net
        return items[key]

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def forward(self, x, *args):
        # containers route through children directly (each child resolves its
        # own deferred params); works identically on NDArray and traced values
        for block in self._children.values():
            x = block(x)
        return x

    def hybrid_forward(self, F, x):
        return self.forward(x)

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        items = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*items[key])
            return net
        return items[key]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """y = act(x·Wᵀ + b) (REF:gluon/nn/basic_layers.py:Dense), MXU matmul."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._use_bias = use_bias
        self.weight = self.params.get("weight", shape=(units, in_units),
                                      dtype=dtype, init=weight_initializer,
                                      allow_deferred_init=True)
        if use_bias:
            self.bias = self.params.get("bias", shape=(units,), dtype=dtype,
                                        init=bias_initializer,
                                        allow_deferred_init=True)
        self.act = Activation(activation) if activation else None

    def infer_shape(self, x, *args):
        in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape_hint((self._units, in_units))

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               no_bias=bias is None, flatten=self._flatten)
        return self.act(out) if self.act else out

    def __repr__(self):
        return f"Dense({self.weight.shape[1] or None} -> {self._units})"


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return f"Dropout(p = {self._rate})"


class BatchNorm(HybridBlock):
    """BatchNorm with running-stat aux state
    (REF:gluon/nn/basic_layers.py:BatchNorm + src/operator/nn/batch_norm.cc).
    Aux mutation flows through the apply-scope updates dict under hybridize —
    the functional replacement for the reference's FMutateInputs."""

    def __init__(self, axis=None, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        # axis=None (the default) resolves against the active
        # tpu_mx.layout.default_layout: 1 for channels-first (the reference's
        # default), -1 under a channels-last block.
        self._axis = _layout_mod.bn_axis() if axis is None else axis
        self._momentum = momentum
        self._eps = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = self.params.get("gamma", shape=shape,
                                     init=gamma_initializer,
                                     allow_deferred_init=True,
                                     grad_req="write" if scale else "null")
        self.beta = self.params.get("beta", shape=shape, init=beta_initializer,
                                    allow_deferred_init=True,
                                    grad_req="write" if center else "null")
        self.running_mean = self.params.get("running_mean", shape=shape,
                                            init=running_mean_initializer,
                                            allow_deferred_init=True,
                                            grad_req="null")
        self.running_var = self.params.get("running_var", shape=shape,
                                           init=running_variance_initializer,
                                           allow_deferred_init=True,
                                           grad_req="null")

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape_hint((c,))

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        ndim = len(x.shape)
        axis = self._axis % ndim
        shape = [1] * ndim
        shape[axis] = x.shape[axis]
        red = tuple(i for i in range(ndim) if i != axis)
        g = gamma if self._scale else F.ones_like(gamma)
        b = beta if self._center else F.zeros_like(beta)
        training = autograd.is_training() and not self._use_global_stats
        if os.environ.get("TPUMX_BN_ONEPASS", "1") != "1":
            return self._legacy_forward(F, x, g, b, running_mean,
                                        running_var, red, shape, training)
        # One-pass f32 statistics + folded scale/bias (r5 byte diet; the
        # r4 roofline showed the bf16 ResNet step HBM-bound with 20.5 ms
        # of convert_reduce fusions).  The legacy two-pass form computes
        # var = mean(square(x - mean)), whose reduce DEPENDS on the mean
        # reduce — two sequential full reads of the activation.  The
        # sum/sum-of-squares form has no such dependency, so XLA sibling-
        # fuses both reductions into ONE read of x.  Stats stay f32
        # end-to-end (the legacy path round-tripped them through bf16 via
        # jnp.mean's upcast-and-cast-back); the normalize applies as a
        # single per-channel scale/bias folded in f32, cast once to
        # x.dtype — so no activation-sized f32 appears anywhere.
        n = 1
        for i in red:
            n *= x.shape[i]
        if training:
            xf = F.cast(x, dtype="float32")
            s1 = F.sum(xf, axis=red)
            s2 = F.sum(F.square(xf), axis=red)
            mean = s1 * (1.0 / n)
            # E[x^2]-E[x]^2 cancellation is benign here (f32 accumulation,
            # post-conv activations are near zero-mean); clamp guards the
            # var>=0 invariant against rounding
            var = F.maximum(s2 * (1.0 / n) - F.square(mean), 0.0)
            m = self._momentum
            with autograd.pause():
                rdt = str(running_mean.dtype)
                new_mean = m * running_mean + \
                    (1 - m) * F.cast(F.BlockGrad(mean), dtype=rdt)
                new_var = m * running_var + \
                    (1 - m) * F.cast(F.BlockGrad(var), dtype=rdt)
                self.running_mean._register_mutation(
                    new_mean._data if hasattr(new_mean, "_data") else new_mean)
                self.running_var._register_mutation(
                    new_var._data if hasattr(new_var, "_data") else new_var)
        else:
            mean = F.cast(running_mean, dtype="float32")
            var = F.cast(running_var, dtype="float32")
        inv = F.rsqrt(var + self._eps)
        scale = inv * F.cast(g, dtype="float32")
        bias = F.cast(b, dtype="float32") - mean * scale
        dt = str(x.dtype)
        return x * F.reshape(F.cast(scale, dtype=dt), shape=shape) + \
            F.reshape(F.cast(bias, dtype=dt), shape=shape)

    def _legacy_forward(self, F, x, g, b, running_mean, running_var, red,
                        shape, training):
        """Pre-r5 two-pass form (TPUMX_BN_ONEPASS=0): kept for the
        on-chip A/B of the one-pass byte diet."""
        if training:
            mean = F.mean(x, axis=red)
            var = F.mean(F.square(x - F.reshape(mean, shape=shape)), axis=red)
            m = self._momentum
            with autograd.pause():
                new_mean = m * running_mean + (1 - m) * F.BlockGrad(mean)
                new_var = m * running_var + (1 - m) * F.BlockGrad(var)
                self.running_mean._register_mutation(
                    new_mean._data if hasattr(new_mean, "_data") else new_mean)
                self.running_var._register_mutation(
                    new_var._data if hasattr(new_var, "_data") else new_var)
        else:
            mean, var = running_mean, running_var
        inv = F.rsqrt(F.reshape(var, shape=shape) + self._eps)
        return (x - F.reshape(mean, shape=shape)) * inv * \
            F.reshape(g, shape=shape) + F.reshape(b, shape=shape)

    def __repr__(self):
        return f"BatchNorm(axis={self._axis}, eps={self._eps}, " \
               f"momentum={self._momentum})"


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = self.params.get("gamma", shape=shape,
                                     init=gamma_initializer,
                                     allow_deferred_init=True,
                                     grad_req="write" if scale else "null")
        self.beta = self.params.get("beta", shape=shape, init=beta_initializer,
                                    allow_deferred_init=True,
                                    grad_req="write" if center else "null")

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape_hint((c,))
        self.beta.shape_hint((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._eps)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._eps = epsilon
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = self.params.get("gamma", shape=shape,
                                     init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta", shape=shape, init=beta_initializer,
                                    allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[1]
        self.gamma.shape_hint((c,))
        self.beta.shape_hint((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._eps)


class GroupNorm(HybridBlock):
    """Group normalization over channel groups (REF:gluon/nn/basic_layers.py
    GroupNorm [ver>=1.6], src/operator/nn/group_norm.cc): NCHW-style input,
    channels split into num_groups, normalized over (group, *spatial) with
    f32 statistics.  gamma/beta are PER GROUP, shape (num_groups,), exactly
    the reference contract — so reference GroupNorm weights load
    unchanged."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 **kwargs):
        super().__init__(**kwargs)
        self._ng = int(num_groups)
        self._eps = epsilon
        shape = (self._ng,)
        self.gamma = self.params.get("gamma", shape=shape,
                                     init=gamma_initializer,
                                     grad_req="write" if scale else "null")
        self.beta = self.params.get("beta", shape=shape, init=beta_initializer,
                                    grad_req="write" if center else "null")

    def hybrid_forward(self, F, x, gamma, beta):
        if x.shape[1] % self._ng:
            # shape known here even when in_channels was given up front
            # (infer_shape only runs for deferred params)
            from ...base import MXNetError
            raise MXNetError(f"GroupNorm: channels {x.shape[1]} not "
                             f"divisible by num_groups {self._ng}")
        return F.GroupNorm(x, gamma, beta, num_groups=self._ng,
                           eps=self._eps)


class Embedding(HybridBlock):
    """Lookup table (REF:gluon/nn/basic_layers.py:Embedding).  `sparse_grad`
    accepted for API parity; gradients are dense scatter-adds on TPU."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self.weight = self.params.get("weight", shape=(input_dim, output_dim),
                                      dtype=dtype, init=weight_initializer)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.flatten(x)

    def __repr__(self):
        return "Flatten"


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self._act_type = activation

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer
        self.alpha = self.params.get("alpha", shape=(1,),
                                     init=alpha_initializer or
                                     initializer.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.gelu(x)


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class ReflectionPad2D(HybridBlock):
    """Reflection padding on H/W of NCHW input
    (REF basic_layers.py:ReflectionPad2D)."""

    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (padding,) * 4  # (left, right, top, bottom)
        self._pad = tuple(int(p) for p in padding)

    def hybrid_forward(self, F, x):
        import jax.numpy as jnp
        from ...ndarray import ops as O
        l, r, t, b = self._pad
        return O._apply(
            lambda a: jnp.pad(a, ((0, 0), (0, 0), (t, b), (l, r)),
                              mode="reflect"),
            [x], "ReflectionPad2D")


class Lambda(Block):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        self._fn = function

    def forward(self, *args):
        return self._fn(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        self._fn = function

    def hybrid_forward(self, F, *args):
        return self._fn(F, *args)
