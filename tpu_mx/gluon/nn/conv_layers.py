"""Gluon conv/pool layers (REF:python/mxnet/gluon/nn/conv_layers.py).

Layout: every layer takes the reference's ``layout=`` kwarg; passing None
picks up the thread-local default from `tpu_mx.layout.default_layout`, so a
whole NCHW-written model can be built channels-last (TPU-preferred) in one
`with` block.  Channels-last weights are O<spatial>I (I<spatial>O for
transpose convs), matching the reference's NHWC convention.
"""
from __future__ import annotations

import numpy as np

from ... import layout as _layout_mod
from ..block import HybridBlock
from .basic_layers import Activation

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose",
           "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D", "AvgPool2D",
           "AvgPool3D", "GlobalMaxPool1D", "GlobalMaxPool2D", "GlobalMaxPool3D",
           "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D"]


def _tuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _Conv(HybridBlock):
    """Shared conv machinery; lowered to `lax.conv_general_dilated` via
    nd.Convolution (REF:src/operator/nn/convolution.cc analog)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 transpose=False, output_padding=0, dtype="float32", **kwargs):
        super().__init__(**kwargs)
        ndim = len(kernel_size)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = kernel_size
        self._strides = _tuple(strides, ndim)
        self._padding = _tuple(padding, ndim)
        self._dilation = _tuple(dilation, ndim)
        self._groups = groups
        self._layout = layout or _layout_mod.get_default_layout(ndim)
        self._channels_last = _layout_mod.is_channels_last(self._layout)
        self._transpose = transpose
        self._output_padding = _tuple(output_padding, ndim)
        wshape = self._weight_shape(in_channels)
        self.weight = self.params.get("weight", shape=wshape, dtype=dtype,
                                      init=weight_initializer,
                                      allow_deferred_init=True)
        if use_bias:
            self.bias = self.params.get("bias", shape=(channels,), dtype=dtype,
                                        init=bias_initializer,
                                        allow_deferred_init=True)
        else:
            self.bias = None
        self.act = Activation(activation) if activation else None

    def _weight_shape(self, c_in):
        if self._transpose:
            io = (c_in, self._channels // self._groups)
        else:
            io = (self._channels, c_in // self._groups if c_in else 0)
        if self._channels_last:
            return (io[0],) + self._kernel + (io[1],)
        return io + self._kernel

    def infer_shape(self, x, *args):
        c_in = x.shape[-1 if self._channels_last else 1]
        self.weight.shape_hint(self._weight_shape(c_in))

    def hybrid_forward(self, F, x, weight, bias=None):
        if self._transpose:
            out = F.Deconvolution(x, weight, bias, kernel=self._kernel,
                                  stride=self._strides, dilate=self._dilation,
                                  pad=self._padding, adj=self._output_padding,
                                  num_filter=self._channels,
                                  num_group=self._groups,
                                  no_bias=bias is None, layout=self._layout)
        else:
            out = F.Convolution(x, weight, bias, kernel=self._kernel,
                                stride=self._strides, dilate=self._dilation,
                                pad=self._padding, num_filter=self._channels,
                                num_group=self._groups, no_bias=bias is None,
                                layout=self._layout)
        return self.act(out) if self.act else out

    def __repr__(self):
        return (f"{type(self).__name__}({self._in_channels or None} -> "
                f"{self._channels}, kernel_size={self._kernel}, "
                f"stride={self._strides}, padding={self._padding})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout=None, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", **kwargs):
        super().__init__(channels, _tuple(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout=None, in_channels=0,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", **kwargs):
        super().__init__(channels, _tuple(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout=None, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", **kwargs):
        super().__init__(channels, _tuple(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout=None,
                 in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", **kwargs):
        super().__init__(channels, _tuple(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         transpose=True, output_padding=output_padding,
                         **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout=None, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", **kwargs):
        super().__init__(channels, _tuple(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         transpose=True, output_padding=output_padding,
                         **kwargs)


class Conv3DTranspose(_Conv):
    """REF conv_layers.py:Conv3DTranspose (NCDHW)."""

    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout=None, in_channels=0,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", **kwargs):
        super().__init__(channels, _tuple(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         transpose=True, output_padding=output_padding,
                         **kwargs)


class _Pool(HybridBlock):
    def __init__(self, pool_size, strides, padding, global_pool, pool_type,
                 layout, ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(**kwargs)
        self._kernel = pool_size
        self._stride = strides if strides is not None else pool_size
        self._pad = padding
        self._global = global_pool
        self._type = pool_type
        self._layout = layout or _layout_mod.get_default_layout(len(pool_size))
        self._convention = "full" if ceil_mode else "valid"
        self._count_include_pad = count_include_pad

    def hybrid_forward(self, F, x):
        return F.Pooling(x, kernel=self._kernel, pool_type=self._type,
                         global_pool=self._global, stride=self._stride,
                         pad=self._pad, pooling_convention=self._convention,
                         count_include_pad=self._count_include_pad,
                         layout=self._layout)

    def __repr__(self):
        if self._global:
            return f"{type(self).__name__}"
        return (f"{type(self).__name__}(size={self._kernel}, "
                f"stride={self._stride}, padding={self._pad})")


def _make_pool(name, ndim, ptype, global_pool):
    if global_pool:
        class GPool(_Pool):
            def __init__(self, layout=None, **kwargs):
                super().__init__((1,) * ndim, None, (0,) * ndim, True, ptype,
                                 layout, **kwargs)
        GPool.__name__ = GPool.__qualname__ = name
        return GPool

    class Pool(_Pool):
        def __init__(self, pool_size=2, strides=None, padding=0, layout=None,
                     ceil_mode=False, **kwargs):
            super().__init__(_tuple(pool_size, ndim),
                             _tuple(strides, ndim) if strides is not None else None,
                             _tuple(padding, ndim), False, ptype, layout,
                             ceil_mode=ceil_mode, **kwargs)
    Pool.__name__ = Pool.__qualname__ = name
    return Pool


MaxPool1D = _make_pool("MaxPool1D", 1, "max", False)
MaxPool2D = _make_pool("MaxPool2D", 2, "max", False)
MaxPool3D = _make_pool("MaxPool3D", 3, "max", False)
AvgPool1D = _make_pool("AvgPool1D", 1, "avg", False)
AvgPool2D = _make_pool("AvgPool2D", 2, "avg", False)
AvgPool3D = _make_pool("AvgPool3D", 3, "avg", False)
GlobalMaxPool1D = _make_pool("GlobalMaxPool1D", 1, "max", True)
GlobalMaxPool2D = _make_pool("GlobalMaxPool2D", 2, "max", True)
GlobalMaxPool3D = _make_pool("GlobalMaxPool3D", 3, "max", True)
GlobalAvgPool1D = _make_pool("GlobalAvgPool1D", 1, "avg", True)
GlobalAvgPool2D = _make_pool("GlobalAvgPool2D", 2, "avg", True)
GlobalAvgPool3D = _make_pool("GlobalAvgPool3D", 3, "avg", True)
