"""Inception-v3 (REF:model_zoo/vision/inception.py — Szegedy et al. 2015,
"Rethinking the Inception Architecture for Computer Vision").  299×299
input; the four mixed-block families (A/B/C/D/E) mirror the reference's
channel plan exactly."""
from .... import layout as _layout_mod
from ...block import HybridBlock
from ... import nn

__all__ = ["Inception3", "inception_v3"]


def _conv(channels, kernel, stride=1, padding=0):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(channels, kernel, stride, padding, use_bias=False))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


class _Branches(HybridBlock):
    """Parallel branches concatenated on the channel axis."""

    def __init__(self, branches, **kwargs):
        super().__init__(**kwargs)
        self.branches = []
        for i, b in enumerate(branches):
            setattr(self, f"b{i}", b)
            self.branches.append(b)
        self._caxis = _layout_mod.channel_axis()

    def hybrid_forward(self, F, x):
        return F.concat(*[b(x) for b in self.branches], dim=self._caxis)


def _make_A(pool_features):
    return _Branches([
        _conv(64, 1),
        _seq(_conv(48, 1), _conv(64, 5, padding=2)),
        _seq(_conv(64, 1), _conv(96, 3, padding=1), _conv(96, 3, padding=1)),
        _seq(nn.AvgPool2D(3, 1, 1), _conv(pool_features, 1)),
    ])


def _make_B():
    return _Branches([
        _conv(384, 3, 2),
        _seq(_conv(64, 1), _conv(96, 3, padding=1), _conv(96, 3, 2)),
        _seq(nn.MaxPool2D(3, 2)),
    ])


def _make_C(channels_7x7):
    c = channels_7x7
    return _Branches([
        _conv(192, 1),
        _seq(_conv(c, 1), _conv(c, (1, 7), padding=(0, 3)),
             _conv(192, (7, 1), padding=(3, 0))),
        _seq(_conv(c, 1), _conv(c, (7, 1), padding=(3, 0)),
             _conv(c, (1, 7), padding=(0, 3)),
             _conv(c, (7, 1), padding=(3, 0)),
             _conv(192, (1, 7), padding=(0, 3))),
        _seq(nn.AvgPool2D(3, 1, 1), _conv(192, 1)),
    ])


def _make_D():
    return _Branches([
        _seq(_conv(192, 1), _conv(320, 3, 2)),
        _seq(_conv(192, 1), _conv(192, (1, 7), padding=(0, 3)),
             _conv(192, (7, 1), padding=(3, 0)), _conv(192, 3, 2)),
        _seq(nn.MaxPool2D(3, 2)),
    ])


class _MixedE(HybridBlock):
    """Mixed 7a/7b: branches whose sub-branches themselves fan out."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.b0 = _conv(320, 1)
        self.b1_stem = _conv(384, 1)
        self.b1a = _conv(384, (1, 3), padding=(0, 1))
        self.b1b = _conv(384, (3, 1), padding=(1, 0))
        self.b2_stem = _seq(_conv(448, 1), _conv(384, 3, padding=1))
        self.b2a = _conv(384, (1, 3), padding=(0, 1))
        self.b2b = _conv(384, (3, 1), padding=(1, 0))
        self.b3 = _seq(nn.AvgPool2D(3, 1, 1), _conv(192, 1))
        self._caxis = _layout_mod.channel_axis()

    def hybrid_forward(self, F, x):
        y1 = self.b1_stem(x)
        y2 = self.b2_stem(x)
        return F.concat(self.b0(x), self.b1a(y1), self.b1b(y1),
                        self.b2a(y2), self.b2b(y2), self.b3(x),
                        dim=self._caxis)


def _seq(*blocks):
    out = nn.HybridSequential()
    for b in blocks:
        out.add(b)
    return out


class Inception3(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        f = nn.HybridSequential()
        f.add(_conv(32, 3, 2))
        f.add(_conv(32, 3))
        f.add(_conv(64, 3, padding=1))
        f.add(nn.MaxPool2D(3, 2))
        f.add(_conv(80, 1))
        f.add(_conv(192, 3))
        f.add(nn.MaxPool2D(3, 2))
        f.add(_make_A(32))
        f.add(_make_A(64))
        f.add(_make_A(64))
        f.add(_make_B())
        f.add(_make_C(128))
        f.add(_make_C(160))
        f.add(_make_C(160))
        f.add(_make_C(192))
        f.add(_make_D())
        f.add(_MixedE())
        f.add(_MixedE())
        f.add(nn.GlobalAvgPool2D())
        f.add(nn.Dropout(0.5))
        self.features = f
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def inception_v3(pretrained=False, classes=1000, **kwargs):
    return Inception3(classes=classes, **kwargs)
