"""SqueezeNet 1.0/1.1 (REF:model_zoo/vision/squeezenet.py)."""
from .... import layout as _layout_mod
from ...block import HybridBlock
from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


def _make_fire(squeeze_channels, expand1x1_channels, expand3x3_channels):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(squeeze_channels, 1, activation="relu"))

    class _Expand(HybridBlock):
        def __init__(self):
            super().__init__()
            self.e1 = nn.Conv2D(expand1x1_channels, 1, activation="relu")
            self.e3 = nn.Conv2D(expand3x3_channels, 3, padding=1,
                                activation="relu")
            self._caxis = _layout_mod.channel_axis()  # channel axis under the
            # active default_layout at build time

        def hybrid_forward(self, F, x):
            return F.concat(self.e1(x), self.e3(x), dim=self._caxis)

    out.add(_Expand())
    return out


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000, **kwargs):
        super().__init__(**kwargs)
        assert version in ("1.0", "1.1")
        self.features = nn.HybridSequential()
        if version == "1.0":
            self.features.add(nn.Conv2D(96, 7, 2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_make_fire(16, 64, 64))
            self.features.add(_make_fire(16, 64, 64))
            self.features.add(_make_fire(32, 128, 128))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_make_fire(32, 128, 128))
            self.features.add(_make_fire(48, 192, 192))
            self.features.add(_make_fire(48, 192, 192))
            self.features.add(_make_fire(64, 256, 256))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_make_fire(64, 256, 256))
        else:
            self.features.add(nn.Conv2D(64, 3, 2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_make_fire(16, 64, 64))
            self.features.add(_make_fire(16, 64, 64))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_make_fire(32, 128, 128))
            self.features.add(_make_fire(32, 128, 128))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_make_fire(48, 192, 192))
            self.features.add(_make_fire(48, 192, 192))
            self.features.add(_make_fire(64, 256, 256))
            self.features.add(_make_fire(64, 256, 256))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.HybridSequential()
        self.output.add(nn.Conv2D(classes, 1, activation="relu"))
        self.output.add(nn.GlobalAvgPool2D())
        self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def squeezenet1_0(**kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(**kw):
    return SqueezeNet("1.1", **kw)
