"""ResNet v1/v2 model zoo (REF:python/mxnet/gluon/model_zoo/vision/resnet.py).

Same architecture family (18/34/50/101/152, BasicBlock/Bottleneck, v1 post-act
and v2 pre-act) — the ResNet-50 ImageNet headline config of BASELINE.md.
NCHW API layout; XLA:TPU re-layouts convolutions internally for the MXU.

Every layer declares its dims (r5): no deferred-shape params means model
build touches the device only for on-device parameter init — no
finalize forward.  The stems therefore pin the 3-channel image contract
(the reference leaves stem in_channels deferred; grayscale input now
fails loudly at the first conv instead of silently specializing).
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["ResNetV1", "ResNetV2", "SpaceToDepthStem", "BasicBlockV1",
           "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "resnet18_v1", "resnet34_v1",
           "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
           "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "get_resnet"]


def _conv3x3(channels, stride, in_channels):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels)


class SpaceToDepthStem(HybridBlock):
    """TPU-friendly ResNet stem (the standard MLPerf-era trick): a 4×4
    space-to-depth on the input image followed by a 3×3 stride-1 conv.

    The classic 7×7/2 conv contracts over 7·7·3 = 147 values with C=3 in
    the 128-wide lane dimension — the MXU runs it ~43× under-filled.  The
    transform moves the 4×4 spatial block into channels (C=3 → 48), so the
    first conv contracts over 3·3·48 = 432 lane-aligned values.  Output is
    (N, 56, 56, C0) — the same shape/stride as conv7x7/2 + maxpool3x3/2,
    with matched ~12×12 receptive field, so the rest of the network is
    untouched.  Select with ``get_resnet(..., stem="s2d")``."""

    def __init__(self, channels, block=4, **kwargs):
        super().__init__(**kwargs)
        from ....layout import get_default_layout, is_channels_last
        self._block = block
        self._nhwc = is_channels_last(get_default_layout(2))
        self.conv = nn.Conv2D(channels, kernel_size=3, strides=1, padding=1,
                              use_bias=False, in_channels=3 * block * block)
        self.bn = nn.BatchNorm(in_channels=channels)

    def hybrid_forward(self, F, x):
        b = self._block
        if self._nhwc:
            N, H, W, C = x.shape
            x = F.reshape(x, shape=(N, H // b, b, W // b, b, C))
            x = F.transpose(x, axes=(0, 1, 3, 2, 4, 5))
            x = F.reshape(x, shape=(N, H // b, W // b, b * b * C))
        else:
            x = F.space_to_depth(x, block_size=b)
        return F.Activation(self.bn(self.conv(x)), act_type="relu")


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(nn.BatchNorm(in_channels=channels))
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(nn.BatchNorm(in_channels=channels))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm(in_channels=channels))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(residual + x, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1, strides=stride,
                                in_channels=in_channels))
        self.body.add(nn.BatchNorm(in_channels=channels // 4))
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4))
        self.body.add(nn.BatchNorm(in_channels=channels // 4))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1,
                                in_channels=channels // 4))
        self.body.add(nn.BatchNorm(in_channels=channels))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm(in_channels=channels))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(x + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm(in_channels=in_channels)
        self.conv1 = _conv3x3(channels, stride, in_channels)
        self.bn2 = nn.BatchNorm(in_channels=channels)
        self.conv2 = _conv3x3(channels, 1, channels)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm(in_channels=in_channels)
        self.conv1 = nn.Conv2D(channels // 4, kernel_size=1, strides=1,
                               use_bias=False, in_channels=in_channels)
        self.bn2 = nn.BatchNorm(in_channels=channels // 4)
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4)
        self.bn3 = nn.BatchNorm(in_channels=channels // 4)
        self.conv3 = nn.Conv2D(channels, kernel_size=1, strides=1,
                               use_bias=False, in_channels=channels // 4)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 stem="classic", **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self.features = nn.HybridSequential()
        if thumbnail:
            self.features.add(_conv3x3(channels[0], 1, 3))
        elif stem == "s2d":
            self.features.add(SpaceToDepthStem(channels[0]))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3, use_bias=False,
                                        in_channels=3))
            self.features.add(nn.BatchNorm(in_channels=channels[0]))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=channels[i]))
        self.features.add(nn.GlobalAvgPool2D())
        self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, in_channels=0):
        layer = nn.HybridSequential()
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 stem="classic", **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self.features = nn.HybridSequential()
        self.features.add(nn.BatchNorm(scale=False, center=False,
                                       in_channels=3))
        if thumbnail:
            self.features.add(_conv3x3(channels[0], 1, 3))
        elif stem == "s2d":
            self.features.add(SpaceToDepthStem(channels[0]))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3, use_bias=False,
                                        in_channels=3))
            self.features.add(nn.BatchNorm(in_channels=channels[0]))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
        in_channels = channels[0]
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=in_channels))
            in_channels = channels[i + 1]
        self.features.add(nn.BatchNorm(in_channels=in_channels))
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes, in_units=in_channels)

    def _make_layer(self, block, layers, channels, stride, in_channels=0):
        layer = nn.HybridSequential()
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, ctx=None, **kwargs):
    assert num_layers in resnet_spec
    assert version in (1, 2)
    block_type, layers, channels = resnet_spec[num_layers]
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        raise RuntimeError("no pretrained weights in this hermetic environment")
    return net


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
