"""Model zoo vision models + get_model registry
(REF:python/mxnet/gluon/model_zoo/vision/__init__.py)."""
# module refs first: the star imports below rebind e.g. `alexnet` to the
# factory function, shadowing the submodule attribute on this package
from . import alexnet as _alexnet
from . import densenet as _densenet
from . import inception as _inception
from . import mobilenet as _mobilenet
from . import resnet as _resnet
from . import squeezenet as _squeezenet
from . import vgg as _vgg

from .resnet import *  # noqa: F401,F403
from .alexnet import *  # noqa: F401,F403
from .vgg import *  # noqa: F401,F403
from .mobilenet import *  # noqa: F401,F403
from .squeezenet import *  # noqa: F401,F403
from .densenet import *  # noqa: F401,F403
from .inception import *  # noqa: F401,F403

_models = {}
for _mod in (_resnet, _alexnet, _vgg, _mobilenet, _squeezenet, _densenet,
             _inception):
    for _name in _mod.__all__:
        _obj = getattr(_mod, _name)
        if callable(_obj) and _name[0].islower():
            _models[_name] = _obj


def get_model(name, **kwargs):
    name = name.lower()
    if name not in _models:
        raise ValueError(
            f"model {name!r} not in model zoo; available: {sorted(_models)}")
    return _models[name](**kwargs)
