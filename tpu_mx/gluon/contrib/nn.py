"""gluon.contrib.nn (REF:python/mxnet/gluon/contrib/nn/basic_layers.py).

Capabilities kept: Concurrent / HybridConcurrent containers, Identity,
SparseEmbedding, SyncBatchNorm, PixelShuffle1D/2D/3D.  TPU-native notes
inline — the interesting one is SyncBatchNorm, which under the compiled
SPMD train step is not a separate kernel at all (see its docstring).
"""
from __future__ import annotations

from ...base import MXNetError
from .. import nn as _nn
from ..block import HybridBlock
from ...ndarray import ops as F

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D",
           "PixelShuffle3D"]


class HybridConcurrent(_nn.HybridSequential):
    """Feed the same input to every child, concat the outputs along `axis`
    (REF contrib/nn: HybridConcurrent — the Inception-branch container)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x, *args):
        # container routing (HybridSequential pattern): every child sees the
        # SAME input, outputs concat along self.axis
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)

    def hybrid_forward(self, Fm, x):
        return self.forward(x)


class Concurrent(HybridConcurrent):
    """Imperative alias (REF contrib/nn: Concurrent); identical here — the
    single Block/HybridBlock split collapses because every op is traceable."""


class Identity(HybridBlock):
    """Pass-through (REF contrib/nn: Identity) — placeholder branch for
    Concurrent containers."""

    def hybrid_forward(self, Fm, x):
        return x


class SparseEmbedding(_nn.Embedding):
    """Embedding with the reference's sparse-gradient intent
    (REF contrib/nn: SparseEmbedding, grad_stype='row_sparse').

    DIVERGENCE (DIVERGENCES.md #5): on TPU the gradient is a dense
    scatter-add produced by XLA — `row_sparse` storage doesn't exist.  The
    API is kept so reference models construct unchanged; memory-wise XLA's
    scatter in the fused backward is the efficient path here.
    """


class SyncBatchNorm(_nn.BatchNorm):
    """Cross-device synchronized BatchNorm (REF contrib/nn: SyncBatchNorm,
    src/operator/contrib/sync_batch_norm.cc — GPU allreduce of per-device
    moments).

    TPU-native design note: under the compiled SPMD train step
    (`CompiledTrainStep`, batch sharded over the `dp` mesh axis) the plain
    `BatchNorm` already IS sync-BN — `mean(x, batch_axes)` runs on the
    logically-global array, and GSPMD partitions it into per-device partial
    sums + an all-reduce over ICI.  There is no second kernel to write;
    this class exists so reference code constructs unchanged, and
    `num_devices` is accepted and ignored (the mesh defines the sync
    group).  The only path where stats are per-device is the eager
    `split_and_load` loop, where the reference synced via NCCL; that eager
    divergence is documented rather than emulated (the compiled step is
    the trainings path).  A test asserts the global-stats property on an
    8-device mesh (tests/test_contrib_layers.py).
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        if num_devices is not None and num_devices <= 0:
            raise MXNetError("num_devices must be positive")
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, **kwargs)


class _PixelShuffle(HybridBlock):
    """r-factor sub-pixel upsample: (N, C·Πr, *S) -> (N, C, *(S·r))
    (REF contrib/nn: PixelShuffle1D/2D/3D).  Pure reshape+transpose —
    XLA folds it into the neighbouring conv's layout assignment."""

    def __init__(self, factor, ndim, **kwargs):
        super().__init__(**kwargs)
        self._factors = (int(factor),) * ndim if isinstance(
            factor, (int, float)) else tuple(int(f) for f in factor)
        if len(self._factors) != ndim:
            raise MXNetError(f"factor must be int or length-{ndim} tuple")
        self._ndim = ndim

    def hybrid_forward(self, Fm, x):
        f = self._factors
        n = self._ndim
        shape = x.shape
        C = shape[1]
        prod = 1
        for v in f:
            prod *= v
        if C % prod:
            raise MXNetError(
                f"PixelShuffle: channels {C} not divisible by {prod}")
        c_out = C // prod
        spatial = shape[2:]
        # (N, c_out, f1..fn, s1..sn) -> interleave -> (N, c_out, s1·f1, ...)
        x = F.reshape(x, shape=(shape[0], c_out) + f + tuple(spatial))
        perm = [0, 1]
        for i in range(n):
            perm.extend([2 + n + i, 2 + i])  # si, fi adjacent
        x = F.transpose(x, axes=tuple(perm))
        out_sp = tuple(s * ff for s, ff in zip(spatial, f))
        return F.reshape(x, shape=(shape[0], c_out) + out_sp)


class PixelShuffle1D(_PixelShuffle):
    def __init__(self, factor, **kwargs):
        super().__init__(factor, 1, **kwargs)


class PixelShuffle2D(_PixelShuffle):
    def __init__(self, factor, **kwargs):
        super().__init__(factor, 2, **kwargs)


class PixelShuffle3D(_PixelShuffle):
    def __init__(self, factor, **kwargs):
        super().__init__(factor, 3, **kwargs)
