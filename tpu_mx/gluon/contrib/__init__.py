"""gluon.contrib (REF:python/mxnet/gluon/contrib/__init__.py): nn layers,
rnn cells, and the Estimator training-loop facade."""
from . import nn
from . import rnn
from . import estimator

__all__ = ["nn", "rnn", "estimator"]
