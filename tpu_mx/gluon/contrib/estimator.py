"""gluon.contrib.estimator (REF:python/mxnet/gluon/contrib/estimator/
{estimator,event_handler}.py [ver>=1.6]).

Capabilities kept: the Estimator fit/evaluate loop with the event-handler
protocol (train_begin / epoch_begin / batch_begin / batch_end / epoch_end /
train_end) and the stock handlers: StoppingHandler, LoggingHandler,
CheckpointHandler, EarlyStoppingHandler, ValidationHandler.  The training
step itself is the framework-native one — `autograd.record` + `backward` +
`Trainer.step`, which under a hybridized net compiles to a single XLA
program — the Estimator is pure Python orchestration around it.
"""
from __future__ import annotations

import logging
import os
import time

import numpy as np

from ... import autograd, metric as metric_mod
from ...base import MXNetError
from ..trainer import Trainer

__all__ = ["Estimator", "EventHandler", "StoppingHandler", "LoggingHandler",
           "CheckpointHandler", "EarlyStoppingHandler", "ValidationHandler"]


class EventHandler:
    """Base event handler: override any subset of the six hooks."""

    def train_begin(self, estimator):
        pass

    def train_end(self, estimator):
        pass

    def epoch_begin(self, estimator):
        pass

    def epoch_end(self, estimator):
        pass

    def batch_begin(self, estimator):
        pass

    def batch_end(self, estimator):
        pass


class StoppingHandler(EventHandler):
    """Stop on max_epoch / max_batch (REF event_handler.py:StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch

    def batch_end(self, estimator):
        if self.max_batch and estimator.global_batch >= self.max_batch:
            estimator.stop_training = True

    def epoch_end(self, estimator):
        if self.max_epoch and estimator.current_epoch + 1 >= self.max_epoch:
            estimator.stop_training = True


class LoggingHandler(EventHandler):
    """Periodic train-metric logging (REF event_handler.py:LoggingHandler)."""

    def __init__(self, log_interval=50, logger=None):
        self.log_interval = log_interval
        self.logger = logger or logging.getLogger("tpu_mx.estimator")
        self._tic = None
        self._count = 0

    def epoch_begin(self, estimator):
        self._tic = time.time()
        self._count = 0

    def batch_end(self, estimator):
        self._count += 1
        if self._count % self.log_interval == 0:
            dt = time.time() - self._tic
            metrics = ", ".join(f"{n}={v:.4f}" for n, v in
                                (m.get() for m in estimator.train_metrics)
                                if np.isfinite(v))
            self.logger.info(
                "epoch %d batch %d: %s (%.1f batch/s)",
                estimator.current_epoch, self._count, metrics,
                self._count / max(dt, 1e-9))


class CheckpointHandler(EventHandler):
    """Save params (+ trainer state) every epoch; keeps `max_checkpoints`
    (REF event_handler.py:CheckpointHandler)."""

    def __init__(self, model_dir, model_prefix="model", max_checkpoints=5,
                 save_best=False, monitor=None, mode="min"):
        if mode not in ("min", "max"):
            raise MXNetError("mode must be 'min' or 'max'")
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.max_checkpoints = max_checkpoints
        self.save_best = save_best
        self.monitor = monitor
        self.mode = mode
        self._saved = []
        self._best = None

    def epoch_end(self, estimator):
        os.makedirs(self.model_dir, exist_ok=True)
        epoch = estimator.current_epoch
        path = os.path.join(self.model_dir,
                            f"{self.model_prefix}-epoch{epoch}.params")
        estimator.net.save_parameters(path)
        self._saved.append(path)
        while len(self._saved) > self.max_checkpoints:
            old = self._saved.pop(0)
            if os.path.exists(old):
                os.remove(old)
        if self.save_best:
            value = self._monitored(estimator)
            better = value is not None and (
                self._best is None or
                (value < self._best if self.mode == "min"
                 else value > self._best))
            if better:
                self._best = value
                estimator.net.save_parameters(os.path.join(
                    self.model_dir, f"{self.model_prefix}-best.params"))

    def _monitored(self, estimator):
        for m in (estimator.val_metrics or estimator.train_metrics):
            name, value = m.get()
            if self.monitor is None or name == self.monitor:
                return value
        return None


class EarlyStoppingHandler(EventHandler):
    """Stop when the monitored metric stops improving
    (REF event_handler.py:EarlyStoppingHandler).  `mode` 'min' or 'max'."""

    def __init__(self, monitor="loss", min_delta=0.0, patience=3,
                 mode="min"):
        if mode not in ("min", "max"):
            raise MXNetError("mode must be 'min' or 'max'")
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.mode = mode
        self._best = None
        self._wait = 0
        self.stopped_epoch = None

    def epoch_end(self, estimator):
        value = None
        for m in (estimator.val_metrics or estimator.train_metrics):
            name, v = m.get()
            if name == self.monitor:
                value = v
        if value is None or not np.isfinite(value):
            return
        better = (self._best is None or
                  (self.mode == "min" and value < self._best - self.min_delta)
                  or (self.mode == "max" and
                      value > self._best + self.min_delta))
        if better:
            self._best = value
            self._wait = 0
        else:
            self._wait += 1
            if self._wait >= self.patience:
                self.stopped_epoch = estimator.current_epoch
                estimator.stop_training = True


class ValidationHandler(EventHandler):
    """Run evaluate() on val_data every `epoch_period` epochs
    (REF event_handler.py:ValidationHandler)."""

    def __init__(self, val_data, eval_fn=None, epoch_period=1):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period

    def epoch_end(self, estimator):
        if (estimator.current_epoch + 1) % self.epoch_period:
            return
        if self.eval_fn is not None:
            self.eval_fn(self.val_data)
        else:
            estimator.evaluate(self.val_data)


class Estimator:
    """Training-loop facade (REF estimator.py:Estimator).

    fit() runs: for each batch — forward under `autograd.record`,
    `backward()`, `Trainer.step(batch_size)` — the same compiled-XLA path
    as a hand-written loop (hybridize the net for one-program steps)."""

    def __init__(self, net, loss, train_metrics=None, trainer=None,
                 context=None):
        self.net = net
        self.loss = loss
        self.train_metrics = self._as_metrics(train_metrics)
        self.val_metrics = []
        self.trainer = trainer or Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})
        self.context = context
        self.stop_training = False
        self.current_epoch = 0
        self.global_batch = 0

    @staticmethod
    def _as_metrics(metrics):
        if metrics is None:
            return [metric_mod.Loss("loss")]
        if not isinstance(metrics, (list, tuple)):
            metrics = [metrics]
        return list(metrics)

    def _update_metrics(self, metrics, labels, preds, losses):
        for m in metrics:
            if isinstance(m, metric_mod.Loss):
                m.update(None, losses)
            else:
                m.update(labels, preds)

    def fit(self, train_data, val_data=None, epochs=1, event_handlers=None,
            batch_fn=None):
        handlers = list(event_handlers or [])
        handlers.append(StoppingHandler(max_epoch=epochs))
        if val_data is not None and not any(
                isinstance(h, ValidationHandler) for h in handlers):
            handlers.append(ValidationHandler(val_data))
        # validation must fire before its consumers (early-stop, save_best)
        # read val_metrics at the same epoch_end — the reference's handler
        # priority ordering; stable sort keeps user order otherwise
        handlers.sort(key=lambda h: 0 if isinstance(h, ValidationHandler)
                      else 1)
        self.stop_training = False
        for h in handlers:
            h.train_begin(self)
        for epoch in range(epochs):
            self.current_epoch = epoch
            for m in self.train_metrics:
                m.reset()
            for h in handlers:
                h.epoch_begin(self)
            for batch in train_data:
                data, label = batch_fn(batch) if batch_fn else batch
                for h in handlers:
                    h.batch_begin(self)
                with autograd.record():
                    out = self.net(data)
                    loss = self.loss(out, label)
                loss.backward()
                bsz = int(np.prod(loss.shape)) or 1
                self.trainer.step(bsz)
                self.global_batch += 1
                self._update_metrics(self.train_metrics, label, out, loss)
                for h in handlers:
                    h.batch_end(self)
                if self.stop_training:
                    break
            for h in handlers:
                h.epoch_end(self)
            if self.stop_training:
                break
        for h in handlers:
            h.train_end(self)
        return self

    def evaluate(self, val_data, metrics=None, batch_fn=None):
        metrics = self._as_metrics(metrics) if metrics is not None \
            else (self.val_metrics or self._as_metrics(None))
        self.val_metrics = metrics
        for m in metrics:
            m.reset()
        for batch in val_data:
            data, label = batch_fn(batch) if batch_fn else batch
            out = self.net(data)
            loss = self.loss(out, label)
            self._update_metrics(metrics, label, out, loss)
        return {m.get()[0]: m.get()[1] for m in metrics}
