"""gluon.contrib.rnn (REF:python/mxnet/gluon/contrib/rnn/{rnn_cell,
conv_rnn_cell}.py).

Capabilities kept: VariationalDropoutCell (same mask across time steps),
LSTMPCell (projection LSTM), Conv{1,2,3}D{RNN,LSTM,GRU}Cell.  All are
expressed over the same `lax.scan`-unrolled RecurrentCell protocol as the
core cells — the conv cells' gates are two `lax.conv_general_dilated`
calls XLA fuses per step.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock
from ..rnn.rnn_cell import LSTMCell, ModifierCell, RecurrentCell
from ...ndarray import ops as F

__all__ = ["VariationalDropoutCell", "LSTMPCell", "Conv1DRNNCell",
           "Conv2DRNNCell", "Conv3DRNNCell", "Conv1DLSTMCell",
           "Conv2DLSTMCell", "Conv3DLSTMCell", "Conv1DGRUCell",
           "Conv2DGRUCell", "Conv3DGRUCell"]


class VariationalDropoutCell(ModifierCell):
    """Variational (locked) dropout: ONE mask per sequence, reused every
    step (REF contrib/rnn: VariationalDropoutCell; Gal & Ghahramani).  The
    masks are drawn lazily on the first step from the shapes observed and
    cached until `reset()`."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0, **kwargs):
        super().__init__(base_cell, **kwargs)
        self._di, self._ds, self._do = drop_inputs, drop_states, drop_outputs
        self._mask_in = None
        self._mask_out = None
        self._mask_states = None

    def reset(self):
        super().reset()
        self._mask_in = self._mask_out = self._mask_states = None

    @staticmethod
    def _draw(rate, like):
        keep = 1.0 - rate
        mask = F.random.bernoulli(prob=keep, shape=like.shape,
                                  dtype=str(like.dtype))
        return mask / keep

    def hybrid_forward(self, Fm, inputs, states):
        from ... import autograd
        training = autograd.is_training()
        if training and self._di > 0:
            if self._mask_in is None:
                self._mask_in = self._draw(self._di, inputs)
            inputs = inputs * self._mask_in
        if training and self._ds > 0:
            if self._mask_states is None:
                self._mask_states = [self._draw(self._ds, s) for s in states]
            states = [s * m for s, m in zip(states, self._mask_states)]
        out, new_states = self.base_cell(inputs, states)
        if training and self._do > 0:
            if self._mask_out is None:
                self._mask_out = self._draw(self._do, out)
            out = out * self._mask_out
        return out, new_states


class LSTMPCell(RecurrentCell):
    """LSTM with a projection of the hidden state (REF contrib/rnn:
    LSTMPCell; Sak et al. 2014) — h = (o ∘ tanh(c)) · W_proj, shrinking
    the recurrent matmul from h²  to h·p."""

    def __init__(self, hidden_size, projection_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, projection_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.h2r_weight = self.params.get(
            "h2r_weight", shape=(projection_size, hidden_size),
            init=h2r_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape_hint((4 * self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, Fm, inputs, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        gates = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                                 num_hidden=4 * self._hidden_size) + \
            F.FullyConnected(states[0], h2h_weight, h2h_bias,
                             num_hidden=4 * self._hidden_size)
        parts = F.split(gates, 4, axis=-1)
        i = F.sigmoid(parts[0])
        f = F.sigmoid(parts[1])
        g = F.tanh(parts[2])
        o = F.sigmoid(parts[3])
        c = f * states[1] + i * g
        r = F.FullyConnected(o * F.tanh(c), h2r_weight, None, no_bias=True,
                             num_hidden=self._projection_size)
        return r, [r, c]


class _ConvRNNBase(RecurrentCell):
    """Shared machinery for the conv cells: gates = conv(x; Wi) +
    conv(h; Wh), state layout NC<spatial> (channels-first like the
    reference's conv cells)."""

    def __init__(self, hidden_channels, kernel, n_gates, ndim,
                 input_shape=None, activation="tanh", **kwargs):
        super().__init__(**kwargs)
        self._hc = hidden_channels
        self._ndim = ndim
        self._kernel = (kernel,) * ndim if isinstance(kernel, int) \
            else tuple(kernel)
        if len(self._kernel) != ndim:
            raise MXNetError(f"kernel must be int or length-{ndim}")
        if any(k % 2 == 0 for k in self._kernel):
            raise MXNetError("conv-RNN kernels must be odd (same-pad)")
        self._pad = tuple(k // 2 for k in self._kernel)
        self._ng = n_gates
        self._activation = activation
        # input_shape=(C, *spatial) — the reference conv cells' ctor arg;
        # with it begin_state()/unroll() work before any forward, without
        # it spatial dims resolve on the first forward
        in_c = 0
        self._spatial = None
        if input_shape is not None:
            input_shape = tuple(int(s) for s in input_shape)
            if len(input_shape) != ndim + 1:
                raise MXNetError(
                    f"input_shape must be (C, {'x'.join('S' * ndim)})")
            in_c = input_shape[0]
            self._spatial = input_shape[1:]
        self.i2h_weight = self.params.get(
            "i2h_weight",
            shape=(n_gates * hidden_channels, in_c) + self._kernel,
            allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight",
            shape=(n_gates * hidden_channels, hidden_channels) + self._kernel,
            allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(n_gates * hidden_channels,), init="zeros",
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(n_gates * hidden_channels,), init="zeros",
            allow_deferred_init=True)

    def infer_shape(self, x, *args):
        self.i2h_weight.shape_hint(
            (self._ng * self._hc, x.shape[1]) + self._kernel)
        self._spatial = tuple(x.shape[2:])

    def state_info(self, batch_size=0):
        if self._spatial is None:
            raise MXNetError(
                f"{type(self).__name__}: spatial state shape unknown — "
                "construct with input_shape=(C, *spatial) or run one "
                "forward before begin_state()/unroll()")
        return [{"shape": (batch_size, self._hc) + self._spatial,
                 "__layout__": "NC" + "DHW"[-self._ndim:]}
                for _ in range(self._n_states)]

    def _gates(self, inputs, h):
        gi = F.Convolution(inputs, self.i2h_weight.data(),
                           self.i2h_bias.data(), kernel=self._kernel,
                           pad=self._pad, num_filter=self._ng * self._hc)
        gh = F.Convolution(h, self.h2h_weight.data(), self.h2h_bias.data(),
                           kernel=self._kernel, pad=self._pad,
                           num_filter=self._ng * self._hc)
        return gi + gh

    def _act(self, x):
        return F.Activation(x, act_type=self._activation)


class _ConvRNNCell(_ConvRNNBase):
    _n_states = 1

    def __init__(self, hidden_channels, kernel, ndim, **kwargs):
        super().__init__(hidden_channels, kernel, 1, ndim, **kwargs)

    def hybrid_forward(self, Fm, inputs, states, **_params):
        self._spatial = tuple(inputs.shape[2:])
        h = self._act(self._gates(inputs, states[0]))
        return h, [h]


class _ConvLSTMCell(_ConvRNNBase):
    _n_states = 2

    def __init__(self, hidden_channels, kernel, ndim, **kwargs):
        super().__init__(hidden_channels, kernel, 4, ndim, **kwargs)

    def hybrid_forward(self, Fm, inputs, states, **_params):
        self._spatial = tuple(inputs.shape[2:])
        parts = F.split(self._gates(inputs, states[0]), 4, axis=1)
        i = F.sigmoid(parts[0])
        f = F.sigmoid(parts[1])
        g = self._act(parts[2])
        o = F.sigmoid(parts[3])
        c = f * states[1] + i * g
        h = o * self._act(c)
        return h, [h, c]


class _ConvGRUCell(_ConvRNNBase):
    _n_states = 1

    def __init__(self, hidden_channels, kernel, ndim, **kwargs):
        super().__init__(hidden_channels, kernel, 3, ndim, **kwargs)

    def hybrid_forward(self, Fm, inputs, states, **_params):
        self._spatial = tuple(inputs.shape[2:])
        h = states[0]
        gi = F.Convolution(inputs, self.i2h_weight.data(),
                           self.i2h_bias.data(), kernel=self._kernel,
                           pad=self._pad, num_filter=3 * self._hc)
        gh = F.Convolution(h, self.h2h_weight.data(), self.h2h_bias.data(),
                           kernel=self._kernel, pad=self._pad,
                           num_filter=3 * self._hc)
        ir, iz, innew = F.split(gi, 3, axis=1)
        hr, hz, hnew = F.split(gh, 3, axis=1)
        r = F.sigmoid(ir + hr)
        z = F.sigmoid(iz + hz)
        n = self._act(innew + r * hnew)
        out = (1 - z) * n + z * h
        return out, [out]


def _make(base, ndim, name):
    def __init__(self, hidden_channels, kernel=3, **kwargs):
        base.__init__(self, hidden_channels, kernel, ndim, **kwargs)
    cls = type(name, (base,), {"__init__": __init__, "__doc__":
                               f"{name} (REF contrib/rnn conv_rnn_cell.py)"})
    return cls


Conv1DRNNCell = _make(_ConvRNNCell, 1, "Conv1DRNNCell")
Conv2DRNNCell = _make(_ConvRNNCell, 2, "Conv2DRNNCell")
Conv3DRNNCell = _make(_ConvRNNCell, 3, "Conv3DRNNCell")
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, "Conv1DLSTMCell")
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, "Conv2DLSTMCell")
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, "Conv3DLSTMCell")
Conv1DGRUCell = _make(_ConvGRUCell, 1, "Conv1DGRUCell")
Conv2DGRUCell = _make(_ConvGRUCell, 2, "Conv2DGRUCell")
Conv3DGRUCell = _make(_ConvGRUCell, 3, "Conv3DGRUCell")
