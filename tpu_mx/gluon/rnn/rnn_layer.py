"""Fused multi-layer RNN/LSTM/GRU (REF:python/mxnet/gluon/rnn/rnn_layer.py over
the fused RNN op REF:src/operator/rnn.cc / cudnn_rnn-inl.h — the PTB path).

TPU-native design (SURVEY §7.3.6): instead of a cuDNN descriptor, each layer
is `lax.scan` over time with the input projection hoisted OUT of the scan —
x·W_i2hᵀ for all T timesteps is one large (T·N, G·H) MXU matmul; the scan body
only carries the (N, G·H) recurrent matmul + gate math, which XLA fuses into
a single per-step kernel.  Memory stays linear in T like the reference's
streaming cuDNN path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..block import HybridBlock
from ...ndarray import NDArray
from ...ndarray.ops import _apply

__all__ = ["RNN", "LSTM", "GRU"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _layer_scan_core(mode, x_tnc, states, wi, wh, bi, bh):
    """One direction of one layer. x_tnc: (T, N, C); states: tuple of (N, H).
    Returns (out (T, N, H), final states)."""
    T, N, _ = x_tnc.shape
    H = wh.shape[1]

    if mode == "lstm":
        # hoisted input projection: one big (T·N, 4H) MXU matmul
        xproj = jnp.einsum("tnc,gc->tng", x_tnc, wi) + bi + bh
        def step(carry, xp):
            h, c = carry
            gates = xp + h @ wh.T
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return (h_new, c_new), h_new

        (h_f, c_f), out = lax.scan(step, (states[0], states[1]), xproj)
        return out, (h_f, c_f)

    if mode == "gru":
        # GRU needs the reset gate applied to h2h of the candidate, so the
        # h2h projection can't be fully merged; split wh by gate.
        # bh is per-gate here (not merged into xproj like lstm/rnn).
        wh_rz, wh_n = wh[:2 * H], wh[2 * H:]
        bh_n = bh[2 * H:]
        xproj = jnp.einsum("tnc,gc->tng", x_tnc, wi) + bi

        def step(h, xp):
            x_rz, x_n = xp[:, :2 * H], xp[:, 2 * H:]
            rz = jax.nn.sigmoid(x_rz + h @ wh_rz.T + bh[:2 * H])
            r, z = jnp.split(rz, 2, axis=-1)
            n = jnp.tanh(x_n + r * (h @ wh_n.T + bh_n))
            h_new = (1 - z) * n + z * h
            return h_new, h_new

        h_f, out = lax.scan(step, states[0], xproj)
        return out, (h_f,)

    act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh
    xproj = jnp.einsum("tnc,gc->tng", x_tnc, wi) + bi + bh

    def step(h, xp):
        h_new = act(xp + h @ wh.T)
        return h_new, h_new

    h_f, out = lax.scan(step, states[0], xproj)
    return out, (h_f,)


def rnn_fused_core(mode, num_layers, bidirectional, dropout, x, init_states,
                   params, rng_key=None, training=False):
    """Full stacked (optionally bidirectional) RNN. x: (T, N, C).
    params: flat list per (layer, dir): [wi, wh, bi, bh, ...].
    init_states: tuple of (L*D, N, H) arrays (h, and c for lstm)."""
    dirs = 2 if bidirectional else 1
    outs = x
    h_finals, c_finals = [], []
    p = 0
    for layer in range(num_layers):
        layer_outs = []
        for d in range(dirs):
            wi, wh, bi, bh = params[p:p + 4]
            p += 4
            idx = layer * dirs + d
            st = tuple(s[idx] for s in init_states)
            inp = jnp.flip(outs, 0) if d == 1 else outs
            out, finals = _layer_scan_core(mode, inp, st, wi, wh, bi, bh)
            if d == 1:
                out = jnp.flip(out, 0)
            layer_outs.append(out)
            h_finals.append(finals[0])
            if mode == "lstm":
                c_finals.append(finals[1])
        outs = layer_outs[0] if dirs == 1 else \
            jnp.concatenate(layer_outs, axis=-1)
        if dropout > 0 and training and layer < num_layers - 1 and \
                rng_key is not None:
            rng_key, sub = jax.random.split(rng_key)
            keep = jax.random.bernoulli(sub, 1 - dropout, outs.shape)
            outs = jnp.where(keep, outs / (1 - dropout), 0.0).astype(outs.dtype)
    h_out = jnp.stack(h_finals)
    if mode == "lstm":
        return outs, h_out, jnp.stack(c_finals)
    return outs, h_out


class _RNNLayer(HybridBlock):
    def __init__(self, mode, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", dtype="float32", **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC")
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._dtype = dtype
        ng = _GATES[mode]
        self._param_names = []
        for layer in range(num_layers):
            for d in range(self._dir):
                suffix = "_l" if d == 0 else "_r"
                in_sz = input_size if layer == 0 else hidden_size * self._dir
                for name, shape, init in [
                        (f"{suffix}{layer}_i2h_weight",
                         (ng * hidden_size, in_sz), i2h_weight_initializer),
                        (f"{suffix}{layer}_h2h_weight",
                         (ng * hidden_size, hidden_size),
                         h2h_weight_initializer),
                        (f"{suffix}{layer}_i2h_bias",
                         (ng * hidden_size,), i2h_bias_initializer),
                        (f"{suffix}{layer}_h2h_bias",
                         (ng * hidden_size,), h2h_bias_initializer)]:
                    p = self.params.get(name, shape=shape, init=init,
                                        allow_deferred_init=True, dtype=dtype)
                    setattr(self, name.lstrip("_"), p)
                    self._param_names.append(name)

    def state_info(self, batch_size=0):
        infos = [{"shape": (self._num_layers * self._dir, batch_size,
                            self._hidden_size), "__layout__": "LNC"}]
        if self._mode == "lstm":
            infos.append(dict(infos[0]))
        return infos

    def cast(self, dtype):
        """Track the compute dtype: the implicit zero states must follow
        the cast or a bf16 net recurs in f32 (the r5 dtype audit caught
        exactly this — f32 states promoted every scan step of the 'bf16'
        PTB leg)."""
        super().cast(dtype)
        self._dtype = dtype

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ...ndarray import ops as F
        return [F.zeros(info["shape"], dtype=self._dtype)
                for info in self.state_info(batch_size)]

    def infer_shape(self, x, *args):
        in_sz = x.shape[-1]
        ng = _GATES[self._mode]
        for layer in range(self._num_layers):
            for d in range(self._dir):
                suffix = "_l" if d == 0 else "_r"
                sz = in_sz if layer == 0 else self._hidden_size * self._dir
                p = self.params[self.prefix +
                                f"{suffix}{layer}_i2h_weight"]
                p.shape_hint((ng * self._hidden_size, sz))

    def forward(self, inputs, states=None):
        from ... import autograd, random as _random
        for name, p in self._reg_params.items():
            if p._data is None and p._shape_incomplete():
                self.infer_shape(inputs)
        # base class finishes deferred init + substitution lookup
        return super().forward(inputs, states)

    def hybrid_forward(self, F, inputs, states=None, **params):
        from ... import autograd, random as _random
        skip_states = states is None
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, 0, 1)
        batch = inputs.shape[1]
        # states follow the PROMOTED compute dtype: a bf16 net on bf16
        # input must not recur in f32 via f32 states (r5 dtype audit),
        # while any mixed call (f32 net on bf16 input, f32 states after
        # cast, ...) recurs in the promoted f32 the dots produce —
        # anything else mismatches the scan carry
        if skip_states:
            sdt = jnp.result_type(inputs.dtype, jnp.dtype(self._dtype))
            states = [F.zeros(info["shape"], dtype=sdt)
                      for info in self.state_info(batch)]
        else:
            sdt = jnp.result_type(inputs.dtype, jnp.dtype(self._dtype),
                                  *[s.dtype for s in states])
            states = [s if s.dtype == sdt else F.cast(s, dtype=sdt)
                      for s in states]
        ordered = [params[n.lstrip("_")] for n in self._param_names]
        training = autograd.is_training()
        key = _random.take_key() if (self._dropout > 0 and training) else None

        mode, nl, bd, dp = self._mode, self._num_layers, self._dir == 2, \
            self._dropout

        def core(x, *flat):
            ns = 2 if mode == "lstm" else 1
            init_states = tuple(flat[:ns])
            ps = list(flat[ns:])
            return rnn_fused_core(mode, nl, bd, dp, x, init_states, ps,
                                  rng_key=key, training=training)

        out = _apply(core, [inputs] + list(states) + ordered,
                     f"RNN[{mode}]")
        outputs, state_outs = out[0], out[1:]
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, 0, 1)
        if skip_states:
            return outputs
        return outputs, list(state_outs)

    def __repr__(self):
        return (f"{type(self).__name__}({self._hidden_size}, "
                f"num_layers={self._num_layers}, layout={self._layout!r}, "
                f"bidirectional={self._dir == 2})")


class RNN(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        super().__init__(f"rnn_{activation}", hidden_size, num_layers, layout,
                         dropout, bidirectional, input_size, **kwargs)


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("lstm", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("gru", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)
