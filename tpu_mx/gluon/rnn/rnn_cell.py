"""Unfused RNN cells (REF:python/mxnet/gluon/rnn/rnn_cell.py).

Single-step cells with the reference's API (begin_state, unroll, __call__).
The fused multi-step path is rnn_layer.py over `lax.scan`; these cells exist
for custom per-step control flow, mirroring the reference's split between
rnn_cell (unfused) and the cuDNN-backed rnn_layer.
"""
from __future__ import annotations

import numpy as np

from ..block import HybridBlock
from ... import initializer as init_mod

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "HybridSequentialRNNCell",
           "BidirectionalCell", "ModifierCell", "DropoutCell",
           "ZoneoutCell", "ResidualCell"]


class RecurrentCell(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ...ndarray import ops as F
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            shape = info["shape"]
            states.append(F.zeros(shape, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Static unroll (reference's symbolic unroll; here the per-step python
        loop is traced once under hybridize so XLA still sees one graph).
        Resets per-sequence cell state first (counters, cached variational
        dropout masks) — the reference unroll does the same."""
        from ...ndarray import ops as F
        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, (list, tuple)):
            steps = list(inputs)
            batch = steps[0].shape[0]
        else:
            batch = inputs.shape[layout.find("N")]
            steps = F.split(inputs, length, axis=axis, squeeze_axis=True)
            if length == 1:
                steps = [steps] if not isinstance(steps, list) else steps
        states = begin_state if begin_state is not None else \
            self.begin_state(batch)
        vl = None
        if valid_length is not None:
            vl = valid_length if hasattr(valid_length, "shape") else \
                F.array(valid_length)
        outputs = []
        for t in range(length):
            out, new_states = self(steps[t], states)
            if vl is not None:
                # reference semantics (SequenceMask + SequenceLast): outputs
                # past a sequence's valid_length are zeroed, and its final
                # states freeze at step valid_length-1
                live = F.reshape(vl > t, shape=(-1,) + (1,) *
                                 (len(out.shape) - 1))
                out = F.where(F.broadcast_to(live, out.shape), out,
                              F.zeros_like(out))
                states = [F.where(F.broadcast_to(
                    F.reshape(vl > t, shape=(-1,) + (1,) *
                              (len(ns.shape) - 1)), ns.shape), ns, s)
                    for s, ns in zip(states, new_states)]
            else:
                states = new_states
            outputs.append(out)
        if merge_outputs or merge_outputs is None:
            outputs = F.stack(*outputs, axis=axis)
        return outputs, states


class RNNCell(RecurrentCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape_hint((self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(RecurrentCell):
    """Gate order i,f,g,o matching the reference's fused RNN op layout."""

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape_hint((4 * self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        gates = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                                 num_hidden=4 * self._hidden_size) + \
            F.FullyConnected(states[0], h2h_weight, h2h_bias,
                             num_hidden=4 * self._hidden_size)
        parts = F.split(gates, 4, axis=-1)
        i = F.sigmoid(parts[0])
        f = F.sigmoid(parts[1])
        g = F.tanh(parts[2])
        o = F.sigmoid(parts[3])
        c = f * states[1] + i * g
        h = o * F.tanh(c)
        return h, [h, c]


class GRUCell(RecurrentCell):
    """Gate order r,z,n (reset/update/new) matching the reference."""

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape_hint((3 * self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i_r, i_z, i_n = F.split(i2h, 3, axis=-1)
        h_r, h_z, h_n = F.split(h2h, 3, axis=-1)
        r = F.sigmoid(i_r + h_r)
        z = F.sigmoid(i_z + h_z)
        n = F.tanh(i_n + r * h_n)
        h = (1 - z) * n + z * states[0]
        return h, [h]


class SequentialRNNCell(RecurrentCell):
    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return sum([c.state_info(batch_size)
                    for c in self._children.values()], [])

    def begin_state(self, batch_size=0, **kwargs):
        return sum([c.begin_state(batch_size, **kwargs)
                    for c in self._children.values()], [])

    def forward(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            inputs, new_s = cell(inputs, states[p:p + n])
            next_states.extend(new_s)
            p += n
        return inputs, next_states

    def hybrid_forward(self, F, inputs, states):
        return self.forward(inputs, states)

    def __len__(self):
        return len(self._children)


class DropoutCell(RecurrentCell):
    def __init__(self, rate, **kwargs):
        super().__init__(**kwargs)
        self._rate = rate

    def state_info(self, batch_size=0):
        return []

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate)
        return inputs, states


class ModifierCell(RecurrentCell):
    """Base for cells that wrap another cell (REF rnn_cell.py:ModifierCell):
    state protocol delegates to the wrapped cell."""

    def __init__(self, base_cell, **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        return self.base_cell.begin_state(batch_size, func=func, **kwargs)

    def reset(self):
        # guard: RecurrentCell.__init__ resets before base_cell is assigned
        super().reset()
        base = getattr(self, "base_cell", None)
        if base is not None:
            base.reset()


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0,
                 **kwargs):
        super().__init__(base_cell, **kwargs)
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        out, new_states = self.base_cell(inputs, states)
        if self._zs > 0:
            new_states = [
                F.where(F.random.uniform(shape=ns.shape) < self._zs, s, ns)
                if hasattr(ns, "shape") else ns
                for s, ns in zip(states, new_states)]
        if self._zo > 0:
            prev = self._prev_output
            if prev is None:
                prev = F.zeros_like(out)
            out = F.where(F.random.uniform(shape=out.shape) < self._zo,
                          prev, out)
            self._prev_output = out
        return out, new_states

    def reset(self):
        super().reset()
        self._prev_output = None


class ResidualCell(ModifierCell):
    def hybrid_forward(self, F, inputs, states):
        out, new_states = self.base_cell(inputs, states)
        return out + inputs, new_states


class HybridSequentialRNNCell(SequentialRNNCell):
    """Alias in this stack (REF rnn_cell.py keeps separate Hybrid/plain
    containers; the single traceable cell protocol here collapses them)."""


class BidirectionalCell(RecurrentCell):
    """Run one cell forward and another backward over the sequence and
    concatenate per-step outputs (REF rnn_cell.py:BidirectionalCell).
    Only usable via `unroll` (a single step has no defined direction,
    exactly the reference's restriction)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_", **kwargs):
        super().__init__(**kwargs)
        self.l_cell = l_cell
        self.r_cell = r_cell
        self._output_prefix = output_prefix

    def state_info(self, batch_size=0):
        return (self.l_cell.state_info(batch_size) +
                self.r_cell.state_info(batch_size))

    def begin_state(self, batch_size=0, func=None, **kwargs):
        return (self.l_cell.begin_state(batch_size, func=func, **kwargs) +
                self.r_cell.begin_state(batch_size, func=func, **kwargs))

    def __call__(self, *args, **kwargs):
        from ...base import MXNetError
        raise MXNetError("BidirectionalCell cannot be stepped one input "
                         "at a time; use unroll()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ...ndarray import ops as F
        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, (list, tuple)):
            steps = list(inputs)
        else:
            steps = F.split(inputs, length, axis=axis, squeeze_axis=True)
            steps = [steps] if length == 1 and not isinstance(steps, list) \
                else list(steps)
        n_l = len(self.l_cell.state_info())
        if begin_state is not None:
            l_states = begin_state[:n_l]
            r_states = begin_state[n_l:]
        else:
            l_states = r_states = None
        l_out, l_states = self.l_cell.unroll(
            length, steps, begin_state=l_states, layout="TNC"
            if False else layout, merge_outputs=False,
            valid_length=valid_length)
        r_out, r_states = self.r_cell.unroll(
            length, list(reversed(steps)), begin_state=r_states,
            layout=layout, merge_outputs=False, valid_length=valid_length)
        outs = [F.concat(lo, ro, dim=-1)
                for lo, ro in zip(l_out if isinstance(l_out, list)
                                  else list(l_out),
                                  list(reversed(r_out if isinstance(
                                      r_out, list) else list(r_out))))]
        if merge_outputs or merge_outputs is None:
            outs = F.stack(*outs, axis=axis)
        return outs, list(l_states) + list(r_states)
