"""gluon.rnn (REF:python/mxnet/gluon/rnn/)."""
from .rnn_cell import (BidirectionalCell, DropoutCell, GRUCell,
                       HybridSequentialRNNCell, LSTMCell, ModifierCell,
                       RecurrentCell, ResidualCell, RNNCell,
                       SequentialRNNCell, ZoneoutCell)
from .rnn_layer import GRU, LSTM, RNN
