"""Gluon Parameter / ParameterDict (REF:python/mxnet/gluon/parameter.py).

Capabilities kept from the reference: deferred (shape-inferred) init,
`grad_req` modes, per-device data access, `shared` params, constant params.
TPU-native addition: a *substitution scope* — during a functional trace
(`Block.apply`, the hybridize/jit path) `param.data()` yields the traced value
injected by the caller instead of the stored buffer, which is what lets one
imperative Gluon definition double as a pure jittable function of its pytree.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp
import numpy as np

from .. import autograd, initializer as init_mod
from ..base import MXNetError
from ..context import current_context
from ..ndarray import NDArray, array

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    pass


from .. import _functional


@contextlib.contextmanager
def param_substitution(mapping, updates=None):
    """mapping: {param_name: raw jax value}; updates collects aux mutations.
    Pushing this scope also switches the op layer into raw-jax mode
    (see tpu_mx._functional)."""
    entry = (mapping, updates if updates is not None else {})
    _functional.push(entry)
    try:
        yield entry[1]
    finally:
        _functional.pop()


def _active_substitution():
    return _functional.top()


class Parameter:
    """A weight/aux tensor owned by Blocks."""

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._data = None          # NDArray
        self._deferred_init_args = None

    # -- init ----------------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._data.drop_grad()
            else:
                self._data.attach_grad(req)

    def _shape_incomplete(self):
        return self.shape is None or any(s in (0, None, -1) for s in self.shape)

    def initialize(self, init=None, ctx=None, default_init=None, force_reinit=False):
        if self._data is not None and not force_reinit:
            return
        if self._shape_incomplete():
            if not self.allow_deferred_init:
                raise DeferredInitializationError(
                    f"Parameter {self.name} has unknown shape {self.shape}")
            self._deferred_init_args = (init, ctx, default_init)
            return
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        initializer = init or self.init or default_init or init_mod.Uniform(0.07)
        if isinstance(initializer, str):
            initializer = init_mod.registry.create(initializer)
        dev = initializer.device_sample(self.name, self.shape, self.dtype) \
            if isinstance(initializer, init_mod.Initializer) else None
        if dev is not None:
            # sampled by the device's own PRNG — wrap directly; routing
            # through array() would round-trip the tensor via host numpy
            self._data = NDArray(dev, ctx=ctx or current_context())
        else:
            data = initializer(self.name, self.shape, self.dtype)
            self._data = array(data, ctx=ctx or current_context(),
                               dtype=self.dtype)
        if self._grad_req != "null":
            self._data.attach_grad(self._grad_req)
        self._deferred_init_args = None

    def _finish_deferred_init(self, shape):
        self.shape = tuple(int(s) for s in shape)
        if self._deferred_init_args is None:
            self._deferred_init_args = (None, None, None)
        self._finish_init(*self._deferred_init_args)

    def shape_hint(self, shape):
        """Fill in unknown dims (0/None) from an observed shape at first call."""
        if self.shape is None:
            self.shape = tuple(shape)
            return
        self.shape = tuple(o if (s in (0, None, -1)) else s
                           for s, o in zip(self.shape, shape))

    # -- access --------------------------------------------------------------
    def data(self, ctx=None):
        sub = _active_substitution()
        if sub is not None and self.name in sub[0]:
            return sub[0][self.name]  # traced value inside functional apply
        if self._data is None:
            if self._deferred_init_args is not None or self._shape_incomplete():
                raise DeferredInitializationError(
                    f"Parameter {self.name} deferred-init pending; run a forward "
                    "pass with real data first")
            raise MXNetError(f"Parameter {self.name} not initialized")
        return self._data

    def list_data(self):
        return [self.data()]

    @property
    def grad(self):
        if self._data is None or self._data.grad is None:
            raise MXNetError(f"Parameter {self.name} has no gradient buffer")
        return self._data.grad

    def list_grad(self):
        return [self.grad]

    def zero_grad(self):
        if self._data is not None and self._data.grad is not None:
            self._data.grad._rebind(jnp.zeros(self._data.shape, self._data.dtype))

    def set_data(self, data):
        if self.shape is not None and len(self.shape) == len(data.shape):
            for want, got in zip(self.shape, data.shape):
                if want not in (0, None, -1) and want != got:
                    raise MXNetError(
                        f"Parameter {self.name}: shape mismatch, declared "
                        f"{self.shape} but got data of shape {tuple(data.shape)}")
        elif self.shape is not None and any(s not in (0, None, -1)
                                            for s in self.shape):
            raise MXNetError(
                f"Parameter {self.name}: rank mismatch, declared {self.shape} "
                f"but got data of shape {tuple(data.shape)}")
        if self._data is None:
            self.shape = tuple(data.shape)
            self._data = data if isinstance(data, NDArray) else array(data)
            if self._grad_req != "null":
                self._data.attach_grad(self._grad_req)
        else:
            self._data._rebind(
                (data._data if isinstance(data, NDArray) else jnp.asarray(data))
                .astype(self._data.dtype).reshape(self._data.shape))

    def _register_mutation(self, new_value):
        """Aux-state write (BatchNorm running stats): eager → in-place rebind;
        inside a trace → recorded into the apply-scope updates dict."""
        sub = _active_substitution()
        if sub is not None:
            sub[1][self.name] = new_value
        else:
            self._data._rebind(jnp.asarray(new_value).astype(self._data.dtype))

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            had_grad = self._data.grad is not None
            self._data = NDArray(self._data._data.astype(dtype))
            if had_grad:
                self._data.attach_grad(self._grad_req)

    def reset_ctx(self, ctx):
        pass  # single logical device per process in the TPU stack; mesh handles spread

    def var(self):
        raise NotImplementedError("symbolic var() is not part of the TPU-native stack")

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"


class Constant(Parameter):
    """Non-differentiable constant parameter (reference: gluon.Constant)."""

    def __init__(self, name, value):
        value = np.asarray(value)
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=str(value.dtype), init=None, differentiable=False)
        self._value = value

    def _finish_init(self, init, ctx, default_init):
        self._data = array(self._value, ctx=ctx or current_context())


class ParameterDict:
    """Ordered name→Parameter mapping with prefix (REF gluon.ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self.prefix = prefix
        self._params = {}
        self._shared = shared

    def get(self, name, **kwargs):
        full = self.prefix + name
        if full in self._params:
            return self._params[full]
        if self._shared is not None and full in self._shared._params:
            self._params[full] = self._shared._params[full]
            return self._params[full]
        p = Parameter(full, **kwargs)
        self._params[full] = p
        return p

    def get_constant(self, name, value=None):
        full = self.prefix + name
        if full not in self._params:
            self._params[full] = Constant(full, value)
        return self._params[full]

    def update(self, other):
        for k, v in other.items():
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        for p in self.values():
            # the global initializer is the DEFAULT, not an override: a
            # parameter's own init (layer weight_initializer, BN ones,
            # constants like the SSD L2-norm scale) takes precedence —
            # REF gluon ParameterDict.initialize passes the global as
            # default_init for exactly this reason
            p.initialize(init=None, ctx=ctx, default_init=init,
                         force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def save(self, fname, strip_prefix=""):
        from ..ndarray import save as nd_save
        payload = {}
        for k, p in self._params.items():
            if p._data is None:
                continue
            key = k[len(strip_prefix):] if k.startswith(strip_prefix) else k
            payload[key] = p.data()
        nd_save(fname, payload)

    def load(self, fname, ctx=None, allow_missing=False, ignore_extra=False,
             restore_prefix=""):
        from ..ndarray import load as nd_load
        loaded = nd_load(fname)
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for k, p in self._params.items():
            if k in loaded:
                p.set_data(loaded[k])
            elif not allow_missing:
                raise MXNetError(f"Parameter {k} missing in file {fname}")
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise MXNetError(f"Extra parameters in file: {sorted(extra)}")

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __getitem__(self, k):
        return self._params[k]

    def __contains__(self, k):
        return k in self._params

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __repr__(self):
        lines = "\n".join(f"  {p!r}" for p in self._params.values())
        return f"ParameterDict({self.prefix}\n{lines}\n)"
