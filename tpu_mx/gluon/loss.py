"""Gluon losses (REF:python/mxnet/gluon/loss.py)."""
from __future__ import annotations

from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "PoissonNLLLoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "SigmoidBinaryCrossEntropyLoss", "SigmoidBCELoss", "KLDivLoss",
           "HuberLoss", "HingeLoss", "SquaredHingeLoss", "LogisticLoss",
           "TripletLoss", "CTCLoss", "CosineEmbeddingLoss", "PassThrough"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return F.reshape(x, shape=y.shape)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return f"{type(self).__name__}(batch_axis={self._batch_axis}, w={self._weight})"


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        ndim = len(loss.shape)
        return F.mean(loss, axis=tuple(i for i in range(ndim)
                                       if i != self._batch_axis))


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ndim = len(loss.shape)
        return F.mean(loss, axis=tuple(i for i in range(ndim)
                                       if i != self._batch_axis))


class SoftmaxCrossEntropyLoss(Loss):
    """REF:gluon/loss.py:SoftmaxCrossEntropyLoss — fused log-softmax + pick."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ndim = len(loss.shape)
        axes = tuple(i for i in range(ndim) if i != self._batch_axis)
        return F.mean(loss, axis=axes) if axes else loss


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class PassThrough(Loss):
    """Identity loss for nets that compute their own scalar objective in
    `forward` (multi-output models whose losses can't ride the step's
    single-output contract: SSD target-matching, MoE's (y, aux) tuple).
    `CompiledTrainStep(net, PassThrough(), ...)` then means "the net's
    first output IS the loss"; extra step args are ignored."""

    def __init__(self, **kwargs):
        super().__init__(weight=None, batch_axis=0, **kwargs)

    def hybrid_forward(self, F, loss, *_ignored):
        return loss


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            # log-sum-exp stable BCE-with-logits
            loss = F.relu(pred) - pred * label + \
                F.Activation(-F.abs(pred), act_type="softrelu")
        else:
            eps = 1e-12
            loss = -(F.log(pred + eps) * label +
                     F.log(1.0 - pred + eps) * (1.0 - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ndim = len(loss.shape)
        return F.mean(loss, axis=tuple(i for i in range(ndim)
                                       if i != self._batch_axis))


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ndim = len(loss.shape)
        return F.mean(loss, axis=tuple(i for i in range(ndim)
                                       if i != self._batch_axis))


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ndim = len(loss.shape)
        return F.mean(loss, axis=tuple(i for i in range(ndim)
                                       if i != self._batch_axis))


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ndim = len(loss.shape)
        return F.mean(loss, axis=tuple(i for i in range(ndim)
                                       if i != self._batch_axis))


class SquaredHingeLoss(HingeLoss):
    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ndim = len(loss.shape)
        return F.mean(loss, axis=tuple(i for i in range(ndim)
                                       if i != self._batch_axis))


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ndim = len(loss.shape)
        return F.mean(loss, axis=tuple(i for i in range(ndim)
                                       if i != self._batch_axis))


class PoissonNLLLoss(Loss):
    """Poisson negative log likelihood (REF gluon/loss.py:PoissonNLLLoss):
    pred is the rate (or its log with from_logits=True); optional Stirling
    term for the ln(label!) constant."""

    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       epsilon=1e-08):
        label = _reshape_like(F, label, pred)
        if self._from_logits:
            loss = F.exp(pred) - label * pred
        else:
            loss = pred - label * F.log(pred + epsilon)
        if self._compute_full:
            stirling = label * F.log(label + epsilon) - label +                 0.5 * F.log(2.0 * 3.14159265358979 * (label + epsilon))
            stirling = F.where(label <= 1.0, F.zeros_like(stirling),
                               stirling)
            loss = loss + stirling
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        ndim = len(pred.shape)
        axes = tuple(range(1, ndim))
        loss = F.sum(F.square(positive - pred) - F.square(negative - pred),
                     axis=axes) + self._margin
        loss = F.relu(loss)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        ndim = len(input1.shape)
        axes = tuple(range(1, ndim))
        num = F.sum(input1 * input2, axis=axes)
        den = F.sqrt(F.sum(F.square(input1), axis=axes)) * \
            F.sqrt(F.sum(F.square(input2), axis=axes))
        cos = num / (den + 1e-12)
        label = F.reshape(label, shape=cos.shape)
        loss = F.where(label == 1, 1.0 - cos, F.relu(cos - self._margin))
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CTCLoss(Loss):
    """CTC (REF:gluon/loss.py:CTCLoss, warp-ctc kernel in the reference) via a
    lax.scan dynamic program — XLA-compilable, O(T·2L)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        super().__init__(weight, 0, **kwargs)
        self._layout = layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        import jax
        import jax.numpy as jnp
        from ..ndarray import NDArray

        def _raw(x):
            return x._data if isinstance(x, NDArray) else (
                None if x is None else jnp.asarray(x))

        raw_label = _raw(label)
        raw_pl = _raw(pred_lengths)
        raw_ll = _raw(label_lengths)

        def ctc(p, lab):
            if self._layout == "NTC":
                p = jnp.swapaxes(p, 0, 1)  # -> (T, N, C)
            T, N, C = p.shape
            logp = jax.nn.log_softmax(p, axis=-1)
            L = lab.shape[1]
            blank = 0
            # extended label seq: blank, l1, blank, l2, ... blank  (2L+1)
            ext = jnp.full((N, 2 * L + 1), blank, dtype=jnp.int32)
            ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
            S = 2 * L + 1
            neg_inf = -1e30
            alpha0 = jnp.full((N, S), neg_inf)
            alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
            alpha0 = alpha0.at[:, 1].set(
                jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0])

            same_as_prev2 = jnp.concatenate(
                [jnp.ones((N, 2), bool),
                 ext[:, 2:] == ext[:, :-2]], axis=1)

            def step(alpha, logp_t):
                a = alpha
                a1 = jnp.concatenate([jnp.full((N, 1), neg_inf), a[:, :-1]], 1)
                a2 = jnp.concatenate([jnp.full((N, 2), neg_inf), a[:, :-2]], 1)
                a2 = jnp.where(same_as_prev2, neg_inf, a2)
                merged = jnp.logaddexp(jnp.logaddexp(a, a1), a2)
                emit = jnp.take_along_axis(logp_t, ext, axis=1)
                return merged + emit, merged + emit

            _, alphas = jax.lax.scan(step, alpha0, logp[1:])
            alphas = jnp.concatenate([alpha0[None], alphas], 0)  # (T, N, S)
            # per-sample end time: pred_lengths-1 (default T-1)
            t_end = (raw_pl.astype(jnp.int32) - 1 if raw_pl is not None
                     else jnp.full((N,), T - 1, jnp.int32))
            alpha_end = jnp.take_along_axis(
                alphas, t_end.reshape(1, N, 1), axis=0)[0]  # (N, S)
            # per-sample final states: 2*label_len and 2*label_len-1
            ll = (raw_ll.astype(jnp.int32) if raw_ll is not None
                  else jnp.full((N,), L, jnp.int32))
            s_last = 2 * ll          # index of trailing blank in ext
            a_blank = jnp.take_along_axis(alpha_end, s_last[:, None], 1)[:, 0]
            a_label = jnp.take_along_axis(
                alpha_end, jnp.maximum(s_last - 1, 0)[:, None], 1)[:, 0]
            return -jnp.logaddexp(a_blank, a_label)

        if isinstance(pred, NDArray):
            from ..ndarray.ops import _apply
            return _apply(lambda p: ctc(p, raw_label), [pred], "CTCLoss")
        return ctc(_raw(pred), raw_label)
