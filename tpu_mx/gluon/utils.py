"""gluon.utils (REF:python/mxnet/gluon/utils.py): split_and_load,
clip_global_norm, download stub."""
from __future__ import annotations

import numpy as np

from ..ndarray import NDArray, array
from ..ndarray import ops as F

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data size {size} not divisible by {num_slice} slices")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(F.slice_axis(data, axis=batch_axis, begin=begin, end=end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """DP batch sharding (reference's per-GPU split; on TPU the pjit path
    shards via NamedSharding instead, but the API is kept for eager loops)."""
    if not isinstance(data, NDArray):
        data = array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays in place so the joint L2 norm <= max_norm (the LM-path
    gradient clip, REF gluon/utils.py:clip_global_norm)."""
    import jax.numpy as jnp
    total = jnp.sqrt(sum(jnp.sum(jnp.square(a._data.astype(jnp.float32)))
                         for a in arrays))
    norm = float(total)
    if check_isfinite and not np.isfinite(norm):
        import warnings
        warnings.warn("nan or inf in clip_global_norm")
    scale = max_norm / max(norm, max_norm)
    if scale < 1.0:
        for a in arrays:
            a._rebind((a._data * scale).astype(a.dtype))
    return norm


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    raise RuntimeError(
        "download() requires network access, unavailable in this environment; "
        "place files locally and pass their path instead")
