"""DataLoader (REF:python/mxnet/gluon/data/dataloader.py).

Capabilities kept: batchify, samplers, multi-worker loading, prefetch.
TPU-native shape: workers are a thread pool feeding a double-buffered
prefetch queue (the PrefetcherIter pattern, REF:src/io/iter_prefetcher.h);
the reference's multiprocessing + cpu_shared-NDArray IPC is unnecessary here
because decode/augment happens in numpy (no GIL-bound tensor math) and the
device transfer is an async `jax.device_put` — the hot path the reference
solved with POSIX-shm is solved by XLA's async H2D pipeline.
"""
from __future__ import annotations

import queue as _queue
import threading

import numpy as np

from ...ndarray import NDArray, array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (REF dataloader.py:default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        transposed = list(zip(*data))
        return tuple(default_batchify_fn(list(t)) for t in transposed)
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return array(arr)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True, timeout=120):
        self._dataset = dataset
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * max(num_workers, 1))

    def _load_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
            return
        yield from self._threaded_iter()

    def _threaded_iter(self):
        """Worker threads + ordered result delivery with bounded prefetch
        (the PrefetcherIter double-buffer analog: at most `prefetch` batches
        in flight, so a slow consumer doesn't pull the whole dataset into
        host RAM)."""
        batches = list(self._batch_sampler)
        results = {}
        results_lock = threading.Lock()
        results_ready = threading.Condition(results_lock)
        task_q = _queue.Queue()
        for seq, indices in enumerate(batches):
            task_q.put((seq, indices))
        stop = threading.Event()
        budget = threading.Semaphore(max(self._prefetch, self._num_workers))

        def worker():
            while not stop.is_set():
                try:
                    seq, indices = task_q.get_nowait()
                except _queue.Empty:
                    return
                while not budget.acquire(timeout=0.1):  # backpressure
                    if stop.is_set():
                        return
                try:
                    batch = self._load_batch(indices)
                except Exception as e:  # surface in consumer
                    batch = e
                with results_ready:
                    results[seq] = batch
                    results_ready.notify_all()

        workers = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self._num_workers)]
        for w in workers:
            w.start()
        try:
            for seq in range(len(batches)):
                with results_ready:
                    while seq not in results:
                        if not results_ready.wait(self._timeout):
                            raise RuntimeError("DataLoader worker timeout")
                    batch = results.pop(seq)
                budget.release()
                if isinstance(batch, Exception):
                    raise batch
                yield batch
        finally:
            stop.set()

    def __len__(self):
        return len(self._batch_sampler)
