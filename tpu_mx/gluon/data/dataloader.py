"""DataLoader (REF:python/mxnet/gluon/data/dataloader.py).

Capabilities kept: batchify, samplers, multi-worker loading, prefetch,
process workers with shared-memory IPC.  TPU-native shape: the default
workers are a thread pool feeding a double-buffered prefetch queue (the
PrefetcherIter pattern, REF:src/io/iter_prefetcher.h) — decode/augment in
numpy releases the GIL and the device transfer is an async
`jax.device_put`.  For PYTHON-heavy transforms that hold the GIL, pass
`thread_pool=False` to get fork()ed process workers that ship batches back
through POSIX shared memory (one segment per batch; the worker writes
through a view with no serialization copy, the parent copies once out of
the segment so it can unlink immediately) — the TPU-native equivalent of
the reference's
`cpu_shared`-context NDArray IPC (REF:src/storage/
cpu_shared_storage_manager.h + dataloader.py worker pool).  Process
workers never touch jax: batches must reach the parent as numpy (the
default batchify does), and the parent does the NDArray wrap + H2D.
"""
from __future__ import annotations

import queue as _queue
import threading

import numpy as np

from ...ndarray import NDArray, array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def _numpy_batchify(data):
    """default_batchify_fn minus the NDArray wrap — what process workers
    run.  jax must not be touched in a fork()ed child, so NDArray samples
    are rejected loudly (converting them would drive the inherited,
    fork-unsafe jax client): return numpy from __getitem__ or use thread
    workers."""
    if isinstance(data[0], NDArray):
        raise TypeError(
            "process workers (thread_pool=False) require numpy samples; "
            "this dataset returns NDArray — return numpy from __getitem__ "
            "or use thread workers (thread_pool=True)")
    if isinstance(data[0], tuple):
        transposed = list(zip(*data))
        return tuple(_numpy_batchify(list(t)) for t in transposed)
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


def _flatten_np(x):
    """(leaves, structure) for nested tuple/list pytrees of arrays.
    Rejects NDArray leaves loudly — this runs in fork()ed workers where
    touching jax (NDArray.__array__ readback) hangs or crashes; the guard
    must hold for CUSTOM batchify fns too, not just the default."""
    if isinstance(x, NDArray):
        raise TypeError(
            "process workers (thread_pool=False) require numpy batches; "
            "got NDArray — return numpy from the dataset/batchify_fn or "
            "use thread workers (thread_pool=True)")
    if isinstance(x, (tuple, list)):
        leaves, struct = [], []
        for e in x:
            l, s = _flatten_np(e)
            leaves.extend(l)
            struct.append(s)
        return leaves, (isinstance(x, tuple), struct)
    return [np.ascontiguousarray(np.asarray(x))], None


def _unflatten(leaves, struct, wrap):
    it = iter(leaves)

    def rebuild(s):
        if s is None:
            return wrap(next(it))
        is_tuple, children = s
        vals = [rebuild(c) for c in children]
        return tuple(vals) if is_tuple else vals

    return rebuild(struct)


def _shm_worker_loop(dataset, batchify, task_q, result_q):
    """Process-worker body: load + batchify (numpy only), write the leaf
    arrays into one fresh POSIX shm segment, send (name, metas) back.  The
    parent owns unlink; the worker closes its mapping immediately."""
    from multiprocessing import shared_memory
    while True:
        item = task_q.get()
        if item is None:
            return
        seq, indices = item
        shm = None
        try:
            batch = batchify([dataset[i] for i in indices])
            leaves, struct = _flatten_np(batch)
            total = max(1, sum(a.nbytes for a in leaves))
            shm = shared_memory.SharedMemory(create=True, size=total)
            off, metas = 0, []
            for a in leaves:
                # write through a view over the segment (no tobytes copy)
                dst = np.frombuffer(shm.buf, dtype=a.dtype, count=a.size,
                                    offset=off).reshape(a.shape)
                dst[...] = a
                del dst
                metas.append((a.dtype.str, a.shape, off))
                off += a.nbytes
            shm.close()
            result_q.put((seq, shm.name, metas, struct, None))
        except Exception as e:  # surfaced in the consumer
            if shm is not None:  # don't leak the segment of a failed batch
                try:
                    shm.close()
                    shm.unlink()
                except Exception:
                    pass
            result_q.put((seq, None, None, None,
                          f"{type(e).__name__}: {e}"))


def default_batchify_fn(data):
    """Stack samples into a batch (REF dataloader.py:default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        transposed = list(zip(*data))
        return tuple(default_batchify_fn(list(t)) for t in transposed)
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return array(arr)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True, timeout=120):
        self._dataset = dataset
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._thread_pool = thread_pool
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * max(num_workers, 1))

    def _load_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
            return
        if self._thread_pool:
            yield from self._threaded_iter()
        else:
            yield from self._process_iter()

    def _process_iter(self):
        """fork()ed process workers + POSIX-shm batch transport with ordered
        delivery and a sliding prefetch window (see module docstring)."""
        import multiprocessing as mp
        from multiprocessing import shared_memory
        ctx = mp.get_context("fork")
        batches = list(self._batch_sampler)
        task_q, result_q = ctx.Queue(), ctx.Queue()
        batchify = self._batchify_fn
        if batchify is default_batchify_fn:
            batchify = _numpy_batchify
        procs = [ctx.Process(target=_shm_worker_loop,
                             args=(self._dataset, batchify, task_q, result_q),
                             daemon=True)
                 for _ in range(self._num_workers)]
        for p in procs:
            p.start()
        window = max(self._prefetch, self._num_workers)
        issued = 0
        pending = {}
        try:
            for _ in range(min(window, len(batches))):
                task_q.put((issued, batches[issued]))
                issued += 1
            for seq in range(len(batches)):
                while seq not in pending:
                    try:
                        got = result_q.get(timeout=self._timeout)
                    except _queue.Empty:
                        dead = [i for i, p in enumerate(procs)
                                if not p.is_alive()]
                        raise RuntimeError(
                            "DataLoader process-worker timeout"
                            + (f"; dead workers: {dead}" if dead else ""))
                    pending[got[0]] = got[1:]
                shm_name, metas, struct, err = pending.pop(seq)
                if err is not None:
                    raise RuntimeError(f"DataLoader worker failed: {err}")
                shm = shared_memory.SharedMemory(name=shm_name)
                try:
                    leaves = []
                    for dtype, shape, off in metas:
                        cnt = int(np.prod(shape, dtype=np.int64)) if shape \
                            else 1
                        view = np.frombuffer(shm.buf, dtype=dtype, count=cnt,
                                             offset=off)
                        leaves.append(np.array(view.reshape(shape)))  # copy
                        del view  # release the exported pointer pre-close
                finally:
                    shm.close()
                    shm.unlink()
                if issued < len(batches):
                    task_q.put((issued, batches[issued]))
                    issued += 1
                batch = _unflatten(leaves, struct, lambda a: array(a))
                yield batch
        finally:
            for _ in procs:
                task_q.put(None)
            for p in procs:
                p.join(timeout=5.0)
                if p.is_alive():
                    p.terminate()
            # unlink every produced-but-unconsumed segment (early generator
            # close / error path) so /dev/shm doesn't fill across epochs
            leftovers = [v[0] for v in pending.values()]
            while True:
                try:
                    got = result_q.get_nowait()
                except _queue.Empty:
                    break
                leftovers.append(got[1])
            for name in leftovers:
                if not name:
                    continue
                try:
                    seg = shared_memory.SharedMemory(name=name)
                    seg.close()
                    seg.unlink()
                except FileNotFoundError:
                    pass

    def _threaded_iter(self):
        """Worker threads + ordered result delivery with bounded prefetch
        (the PrefetcherIter double-buffer analog: at most `prefetch` batches
        in flight, so a slow consumer doesn't pull the whole dataset into
        host RAM)."""
        batches = list(self._batch_sampler)
        results = {}
        results_lock = threading.Lock()
        results_ready = threading.Condition(results_lock)
        task_q = _queue.Queue()
        for seq, indices in enumerate(batches):
            task_q.put((seq, indices))
        stop = threading.Event()
        budget = threading.Semaphore(max(self._prefetch, self._num_workers))

        def worker():
            while not stop.is_set():
                try:
                    seq, indices = task_q.get_nowait()
                except _queue.Empty:
                    return
                while not budget.acquire(timeout=0.1):  # backpressure
                    if stop.is_set():
                        return
                try:
                    batch = self._load_batch(indices)
                except Exception as e:  # surface in consumer
                    batch = e
                with results_ready:
                    results[seq] = batch
                    results_ready.notify_all()

        workers = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self._num_workers)]
        for w in workers:
            w.start()
        try:
            for seq in range(len(batches)):
                with results_ready:
                    while seq not in results:
                        if not results_ready.wait(self._timeout):
                            raise RuntimeError("DataLoader worker timeout")
                    batch = results.pop(seq)
                budget.release()
                if isinstance(batch, Exception):
                    raise batch
                yield batch
        finally:
            stop.set()

    def __len__(self):
        return len(self._batch_sampler)
