"""gluon.data (REF:python/mxnet/gluon/data/__init__.py)."""
from .dataset import ArrayDataset, Dataset, RecordFileDataset, SimpleDataset
from .sampler import BatchSampler, RandomSampler, Sampler, SequentialSampler
from .dataloader import DataLoader
from . import vision
