"""Vision datasets (REF:python/mxnet/gluon/data/vision/datasets.py).

MNIST/FashionMNIST/CIFAR read the standard on-disk formats when present
(no network in this environment — reference downloads; here `root` must
contain the files, else a deterministic synthetic fallback is produced so
examples/tests run hermetically).
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..dataset import ArrayDataset, Dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100", "ImageRecordDataset",
           "ImageFolderDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)


def _synthetic_images(n, shape, num_classes, seed):
    """Deterministic class-separable synthetic data (hermetic fallback)."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, n).astype(np.int32)
    protos = rng.uniform(0, 255, (num_classes,) + shape).astype(np.float32)
    imgs = protos[labels] + rng.normal(0, 16, (n,) + shape).astype(np.float32)
    return np.clip(imgs, 0, 255).astype(np.uint8), labels


class MNIST(_DownloadedDataset):
    """MNIST in idx-ubyte format (REF datasets.py:MNIST)."""

    _shape = (28, 28, 1)
    _nclass = 10
    _files = {True: ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"),
              False: ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")}
    _synthetic_n = {True: 6000, False: 1000}

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        img_file, lbl_file = self._files[self._train]
        img_path = os.path.join(self._root, img_file)
        lbl_path = os.path.join(self._root, lbl_file)
        if os.path.exists(img_path) and os.path.exists(lbl_path):
            with gzip.open(lbl_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                label = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int32)
            with gzip.open(img_path, "rb") as f:
                _, n, rows, cols = struct.unpack(">IIII", f.read(16))
                data = np.frombuffer(f.read(), dtype=np.uint8).reshape(
                    n, rows, cols, 1)
        else:
            data, label = _synthetic_images(
                self._synthetic_n[self._train], self._shape, self._nclass,
                seed=42 if self._train else 43)
        self._data = data
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    _shape = (32, 32, 3)
    _nclass = 10
    _synthetic_n = {True: 5000, False: 1000}

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _read_batch(self, filename):
        raw = np.fromfile(filename, dtype=np.uint8).reshape(-1, 3073)
        return raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            raw[:, 0].astype(np.int32)

    def _get_data(self):
        files = [f"data_batch_{i}.bin" for i in range(1, 6)] if self._train \
            else ["test_batch.bin"]
        paths = [os.path.join(self._root, "cifar-10-batches-bin", f)
                 for f in files]
        if all(os.path.exists(p) for p in paths):
            parts = [self._read_batch(p) for p in paths]
            self._data = np.concatenate([p[0] for p in parts])
            self._label = np.concatenate([p[1] for p in parts])
        else:
            self._data, self._label = _synthetic_images(
                self._synthetic_n[self._train], self._shape, self._nclass,
                seed=44 if self._train else 45)


class CIFAR100(CIFAR10):
    _nclass = 100

    def __init__(self, root="~/.mxnet/datasets/cifar100", fine_label=False,
                 train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _get_data(self):
        self._data, self._label = _synthetic_images(
            self._synthetic_n[self._train], self._shape,
            self._nclass if self._fine else 20, seed=46 if self._train else 47)


class ImageRecordDataset(Dataset):
    """Images from a RecordIO pack (REF datasets.py:ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        from ....recordio import IndexedRecordIO, unpack_img
        self._record = IndexedRecordIO(filename + ".idx", filename, "r")
        self._flag = flag
        self._transform = transform
        self._unpack = unpack_img

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        record = self._record.read_idx(self._record.keys[idx])
        header, img = self._unpack(record)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """class-per-subfolder image tree (REF datasets.py:ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if filename.lower().endswith((".jpg", ".jpeg", ".png", ".npy")):
                    self.items.append((os.path.join(path, filename), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        path, label = self.items[idx]
        if path.endswith(".npy"):
            img = np.load(path)
        else:
            from ....image import imread
            img = imread(path).asnumpy()
        if self._transform is not None:
            return self._transform(img, label)
        return img, label
