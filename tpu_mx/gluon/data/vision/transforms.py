"""Vision transforms (REF:python/mxnet/gluon/data/vision/transforms.py).
Numpy-based host-side augment (the C++ ImageAugmenter analog lives host-side
by design: TPU chips don't decode JPEGs; keep the host CPU pipeline lean)."""
from __future__ import annotations

import numpy as np
from ....random import host_rng as _host_rng

from ...block import Block
from ....ndarray import NDArray, array

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation",
           "RandomLighting", "RandomHue", "RandomColorJitter"]


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


class _Transform(Block):
    def forward(self, x):
        raise NotImplementedError

    def __call__(self, x, *args):
        out = self.forward(x)
        if args:
            return (out,) + args
        return out


class Compose(_Transform):
    def __init__(self, transforms):
        super().__init__()
        self._transforms = transforms

    def forward(self, x):
        for t in self._transforms:
            x = t(x)
        return x


class Cast(_Transform):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return _as_np(x).astype(self._dtype)


class ToTensor(_Transform):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (REF transforms.py:ToTensor)."""

    def forward(self, x):
        x = _as_np(x).astype(np.float32) / 255.0
        if x.ndim == 3:
            return x.transpose(2, 0, 1)
        return x.transpose(0, 3, 1, 2)


class Normalize(_Transform):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self._std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def forward(self, x):
        return (_as_np(x) - self._mean) / self._std


def _resize(img, size):
    """Bilinear resize in numpy (OpenCV analog without the dependency)."""
    h, w = img.shape[:2]
    if isinstance(size, int):
        size = (size, size)
    ow, oh = size
    ys = np.linspace(0, h - 1, oh)
    xs = np.linspace(0, w - 1, ow)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    img = img.astype(np.float32)
    out = (img[y0][:, x0] * (1 - wy) * (1 - wx) +
           img[y1][:, x0] * wy * (1 - wx) +
           img[y0][:, x1] * (1 - wy) * wx +
           img[y1][:, x1] * wy * wx)
    return out


class Resize(_Transform):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size

    def forward(self, x):
        return _resize(_as_np(x), self._size)


class CenterCrop(_Transform):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        x = _as_np(x)
        h, w = x.shape[:2]
        cw, ch = self._size
        x0 = max((w - cw) // 2, 0)
        y0 = max((h - ch) // 2, 0)
        return x[y0:y0 + ch, x0:x0 + cw]


class RandomResizedCrop(_Transform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        x = _as_np(x)
        h, w = x.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = _host_rng().uniform(*self._scale) * area
            aspect = _host_rng().uniform(*self._ratio)
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if cw <= w and ch <= h:
                x0 = _host_rng().randint(0, w - cw + 1)
                y0 = _host_rng().randint(0, h - ch + 1)
                crop = x[y0:y0 + ch, x0:x0 + cw]
                return _resize(crop, self._size)
        return _resize(x, self._size)


class RandomFlipLeftRight(_Transform):
    def forward(self, x):
        x = _as_np(x)
        return x[:, ::-1].copy() if _host_rng().rand() < 0.5 else x


class RandomFlipTopBottom(_Transform):
    def forward(self, x):
        x = _as_np(x)
        return x[::-1].copy() if _host_rng().rand() < 0.5 else x


class RandomBrightness(_Transform):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        alpha = 1.0 + _host_rng().uniform(-self._b, self._b)
        return np.clip(_as_np(x).astype(np.float32) * alpha, 0, 255)


class RandomContrast(_Transform):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        x = _as_np(x).astype(np.float32)
        alpha = 1.0 + _host_rng().uniform(-self._c, self._c)
        gray = x.mean()
        return np.clip(gray + alpha * (x - gray), 0, 255)


class RandomSaturation(_Transform):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        x = _as_np(x).astype(np.float32)
        alpha = 1.0 + _host_rng().uniform(-self._s, self._s)
        gray = x.mean(axis=-1, keepdims=True)
        return np.clip(gray + alpha * (x - gray), 0, 255)


class RandomHue(_Transform):
    """REF transforms.py:RandomHue — YIQ-rotation hue jitter (same math
    as image.HueJitterAug)."""

    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        from ....image.image import HueJitterAug
        out = HueJitterAug(self._h)(_as_np(x).astype(np.float32))
        return np.clip(np.asarray(out.asnumpy()), 0, 255)


class RandomColorJitter(_Transform):
    """REF transforms.py:RandomColorJitter — brightness/contrast/
    saturation/hue in one transform."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        # reference applies the jitters in RANDOM order per sample
        ts = list(self._ts)
        _host_rng().shuffle(ts)
        for t in ts:
            x = t.forward(_as_np(x))
        return x


class RandomLighting(_Transform):
    """PCA-noise lighting (AlexNet-style, REF transforms.py:RandomLighting)."""

    _eigval = np.array([55.46, 4.794, 1.148], np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        x = _as_np(x).astype(np.float32)
        alpha = _host_rng().normal(0, self._alpha, 3).astype(np.float32)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return np.clip(x + rgb, 0, 255)
