"""KVStore: the data-parallel communication facade (REF:src/kvstore/**,
REF:python/mxnet/kvstore.py).

TPU-native mapping (SURVEY §2.3, §5.8): the reference's device ring/NCCL
reduce and the ps-lite parameter server both become *XLA collectives compiled
into the step function* — there is no server role on a TPU pod.  This module
keeps the reference's push/pull API working:

- `local` / `device`: in-process aggregation — push sums the per-device grad
  list (the CommDevice/CommCPU analog), pull broadcasts;
- `nccl`: alias of `device` (ICI collectives replace NCCL);
- `dist_sync` / `dist_sync_device`: multi-host SPMD via `jax.distributed` —
  rank = process_index, num_workers = process_count; the aggregation itself
  rides the `psum` inside a pjit-ed train step (see tpu_mx.parallel);
- `dist_async`: **semantic divergence documented** — XLA collectives are bulk
  synchronous, so dist_async degrades to dist_sync semantics (SURVEY §7.3.3).

Optimizer offload (`set_optimizer`, the PS server-side update) runs locally:
with no server tier, `update_on_kvstore` simply applies the updater here.
"""
from __future__ import annotations

import pickle

from .base import MXNetError, get_env
from .ndarray import NDArray
from .optimizer import Updater, create as _create_optimizer
from . import telemetry as _telemetry
from . import tracing as _tracing

__all__ = ["KVStore", "IntegrityError", "create", "dist_init"]


class IntegrityError(MXNetError):
    """A pulled aggregate no longer matches the checksum recorded when it
    was pushed: the payload was silently corrupted between the sync
    seam's two ends (flaky host memory, a bad transport, a defective
    chip).  Loud by design — this is the SDC defense's kvstore arm
    (ISSUE 20, docs/robustness.md "Silent data corruption defense"), and
    the same verify-on-pull gate a future lossy/quantized sync must
    cross with its *post-decompression* payload."""


def _payload_checksum(arr):
    """crc32 of the payload's exact bytes (None when the leaf has no
    readable buffer — never break push/pull for exotic types)."""
    import zlib
    import numpy as np
    try:
        host = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
        return zlib.crc32(np.ascontiguousarray(host).tobytes())
    except Exception:
        return None


def _nbytes(arr):
    """Payload size of an NDArray/array-like, for the transfer counters
    (best-effort: a 0 for exotic leaves beats breaking push/pull)."""
    try:
        import numpy as np
        return int(arr.size) * int(np.dtype(arr.dtype).itemsize)
    except Exception:
        return 0


def dist_init():
    """Ensure membership in the launcher's collective group (see
    base.dist_boot; `import tpu_mx` already boots it)."""
    from .base import dist_boot
    return dist_boot()


class KVStore:
    def __init__(self, kind="local"):
        self.type = kind
        self._store = {}
        self._checksums = {}   # key -> crc32 recorded at push commit
        self._updater = None
        self._optimizer = None
        self._compression = None
        self._is_dist = kind.startswith("dist")
        self._fleet_token = None
        if self._is_dist:
            import jax
            # multi-host boot: jax.distributed.initialize must have been called
            # by the launcher (tpu_mx.tools.launch analog of tools/launch.py)
            try:
                self._rank = jax.process_index()
                self._num_workers = jax.process_count()
            except Exception:
                self._rank, self._num_workers = 0, 1
            from .parallel import fleet as _fleet
            self._fleet_token = _fleet.generation_token()
        else:
            self._rank, self._num_workers = 0, 1

    # -- identity -------------------------------------------------------------
    def _refresh_world(self):
        """Invalidate the cached rank/world-size when the fleet membership
        epoch moved (ISSUE 17 bugfix: these were cached at init and repr/
        aggregation never re-read them — a resharded run would silently
        aggregate with the stale world size).  Cheap: a token compare per
        access; the re-read happens only on a generation bump."""
        if not self._is_dist:
            return
        from .parallel import fleet as _fleet
        token = _fleet.generation_token()
        if token == self._fleet_token:
            return
        self._fleet_token = token
        import jax
        try:
            self._rank = jax.process_index()
            self._num_workers = jax.process_count()
        except Exception:
            pass
        # the fleet's membership epoch is the world-size authority while
        # one is live (jax.process_count is the static launch-time world)
        live = _fleet.live_world_size()
        if live:
            self._num_workers = int(live)

    @property
    def rank(self):
        self._refresh_world()
        return self._rank

    @property
    def num_workers(self):
        self._refresh_world()
        return self._num_workers

    # -- core API -------------------------------------------------------------
    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            self._store[k] = v[0].copy() if isinstance(v, list) else v.copy()

    def push(self, key, value, priority=0):
        """Aggregate gradients: sum over the per-device list (CommDevice
        analog), then — for `dist_*` stores — a *real* cross-process reduce
        (REF:src/kvstore/kvstore_dist.h push → ps-lite server-side sum;
        REF:tests/nightly/dist_sync_kvstore.py asserts this math).  The jitted
        train-step path uses an in-program psum instead; this covers eager
        push/pull.  Compression (2-bit sim) is applied per-worker before the
        reduce, matching the reference's worker→server message compression."""
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            self._check_inited(k)
            vlist = v if isinstance(v, list) else [v]
            _telemetry.counter("kvstore.pushes").inc()
            _telemetry.counter("kvstore.push_bytes").inc(
                sum(_nbytes(x) for x in vlist))
            agg = vlist[0]
            for extra in vlist[1:]:
                agg = agg + extra
            if self._compression is not None:
                agg = self._compression.compress_decompress(agg)
            agg = self._global_sum(agg)
            if self._updater is not None:
                self._updater(k, agg, self._store[k])
                committed = self._store[k]
            else:
                self._store[f"_pending_{k}"] = agg
                committed = agg
            # integrity seam (ISSUE 20): record the committed payload's
            # checksum at push time; pull verifies it before handing the
            # bytes out.  Cheap relative to this eager parity path (which
            # already round-trips host), and exactly the gate a future
            # quantized sync must also cross.
            crc = _payload_checksum(committed)
            if crc is not None:
                self._checksums[k] = crc
                _telemetry.counter("kvstore.checksums").inc()

    def _global_sum(self, agg):
        """Eager cross-process sum: allgather over the process group, reduce
        on host.  Every rank must call push with the same keys in the same
        order (the reference's bulk-synchronous contract)."""
        if not self._is_dist or self.num_workers <= 1:
            return agg
        if get_env("TPUMX_STRICT_KVSTORE", "0") == "1":
            # VERDICT r3 weak#6: reference-habit `kvstore.push/pull` in the
            # training loop silently trains slow; under the strict flag it
            # fails loudly instead of degrading
            raise MXNetError(
                "eager dist KVStore push is the slow parity path "
                "(allgather-per-key + host reduce) and "
                "TPUMX_STRICT_KVSTORE=1 is set: move gradient reduction "
                "into the compiled step (parallel.CompiledTrainStep / "
                "Trainer without update_on_kvstore), or unset the flag to "
                "accept the degraded path")
        if not getattr(self, "_warned_eager_dist", False):
            self._warned_eager_dist = True
            import logging
            logging.getLogger(__name__).warning(
                "dist KVStore eager push: allgather-per-key with a host-side "
                "reduce (W× reduce bytes, one collective per key). This is "
                "the parity/debug path — at scale use "
                "parallel.CompiledTrainStep, whose psum compiles into the "
                "step and rides ICI (Trainer with update_on_kvstore on a "
                "dist_* store takes THIS slow path)")
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(agg._data)  # (W, ...)
        return NDArray(jnp.asarray(gathered).sum(axis=0).astype(agg.dtype))

    def _check_inited(self, key):
        """Reference contract (REF:src/kvstore/kvstore_local.h CHECK on
        init): push/pull on a key nobody init'ed is a usage error — raise
        the framework's error type with the fix, not a bare KeyError."""
        if key not in self._store:
            raise MXNetError(
                f"key {key!r} not initialized; call kv.init first")

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = self._normalize(key, out)
        for k, o in zip(keys, outs):
            self._check_inited(k)
            pending = self._store.pop(f"_pending_{k}", None)
            src = self._store[k] if pending is None else pending
            if self._updater is None and pending is not None:
                self._store[k] = pending
            # verify-on-pull (ISSUE 20): the bytes handed out must be the
            # bytes committed at push time — a mismatch is silent data
            # corruption crossing the sync seam, raised loudly instead of
            # training on it
            expect = self._checksums.get(k)
            if expect is not None:
                actual = _payload_checksum(src)
                if actual is not None and actual != expect:
                    _telemetry.counter("kvstore.checksum_failures").inc()
                    _tracing.emit("kvstore.checksum_fail", key=str(k))
                    raise IntegrityError(
                        f"kvstore pull({k!r}): payload checksum mismatch "
                        f"(pushed crc32={expect:#010x}, pulled "
                        f"crc32={actual:#010x}) — the aggregate was "
                        "silently corrupted after its push committed; "
                        "refusing to hand out poisoned bytes (SDC "
                        "defense, docs/robustness.md)")
            olist = o if isinstance(o, list) else [o]
            _telemetry.counter("kvstore.pulls").inc()
            _telemetry.counter("kvstore.pull_bytes").inc(
                _nbytes(src) * len(olist))
            for dst in olist:
                src.copyto(dst)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        self.pull(key, out, priority)  # sparse degenerate: dense on TPU

    # -- optimizer offload ----------------------------------------------------
    def set_optimizer(self, optimizer):
        """Reference pickles the optimizer to PS servers; here the 'server' is
        in-process (round-trip through pickle kept to preserve the contract
        that the optimizer must be picklable)."""
        self._optimizer = pickle.loads(pickle.dumps(optimizer))
        self._updater = Updater(self._optimizer)

    def set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        from .contrib.compression import GradientCompression
        self._compression = GradientCompression(**compression_params)

    # -- persistence (reference: save/load optimizer states on rank 0) --------
    def save_optimizer_states(self, fname, dump_optimizer=False):
        """Atomic dump of the updater's per-key states; with
        `dump_optimizer=True` the optimizer OBJECT rides along too
        (reference parity: the PS server pickled both, so a restore on a
        fresh process needs no set_optimizer call first)."""
        states = self._updater.get_states() if self._updater else {}
        if dump_optimizer:
            payload = {"__tpumx_format__": "kvstore-states-v2",
                       "states": states, "optimizer": self._optimizer}
        else:
            payload = states
        from .checkpoint import atomic_write
        with atomic_write(fname) as f:
            f.write(pickle.dumps(payload))

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            payload = pickle.load(f)
        if isinstance(payload, dict) and \
                payload.get("__tpumx_format__") == "kvstore-states-v2":
            if payload["optimizer"] is not None:
                self._optimizer = payload["optimizer"]
                self._updater = Updater(self._optimizer)
            if self._updater:
                self._updater.set_states(payload["states"])
            return
        if self._updater:
            self._updater.set_states(payload)

    def barrier(self):
        if self._is_dist:
            import jax
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("kvstore_barrier")

    def _barrier(self):
        self.barrier()

    @staticmethod
    def _normalize(key, value):
        if isinstance(key, (list, tuple)):
            return list(key), list(value)
        return [key], [value]

    def __repr__(self):
        return f"KVStore(type={self.type}, rank={self.rank}/{self.num_workers})"


def create(name="local"):
    """mx.kv.create — accepted types mirror the reference
    (REF:include/mxnet/kvstore.h KVStore::Create)."""
    valid = {"local", "local_allreduce_cpu", "local_allreduce_device", "device",
             "nccl", "dist", "dist_sync", "dist_async", "dist_sync_device",
             "dist_async_device", "dist_device_sync", "horovod", "p3"}
    if name not in valid:
        raise MXNetError(f"unknown KVStore type {name}")
    return KVStore(name)
