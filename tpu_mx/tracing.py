"""Flight recorder: step-scoped structured events + the crash black box.

The supervisor (docs/robustness.md) and deterministic resume make runs
*survivable* and *replayable*, but the *why* of a restart, rollback or
degrade used to be scattered: telemetry holds cumulative aggregates with
no step identity, the chrome-trace holds spans with no failure context,
and the supervisor's decisions lived only in transient log lines.  This
module is the forensic substrate:

- **Events** — :func:`emit` appends one typed record to a bounded
  in-memory ring buffer.  Every event carries the process-wide **trace
  context** (``run_id``, ``epoch``, ``step``, supervisor ``generation``,
  set by the training loop via :func:`set_context`) plus a payload whose
  fields are declared in the static :data:`KNOWN_EVENTS` catalog — event
  names are an API exactly like ``telemetry.KNOWN_METRICS`` (the
  tpumx-lint ``telemetry-catalog`` pass checks ``emit`` call sites
  statically, docs/static_analysis.md).  The context is deliberately
  process-global, not thread-local: the supervisor runs steps on a
  watchdog daemon thread, and an event emitted there must still carry
  the step that hung.
- **Ring buffer** — a ``collections.deque(maxlen=capacity)`` under one
  lock: sustained emit is O(1) and memory is bounded no matter how long
  the run; :func:`snapshot` copies it consistently.  Overflow is counted
  (``stats()['dropped']``), never silent.
- **Black box** — :func:`dump_blackbox` persists the last N events, a
  full telemetry snapshot, the live trace context and an environment
  fingerprint as ``<prefix>-blackbox.json`` through
  ``checkpoint.atomic_write`` (a crash mid-dump cannot tear it).  The
  supervisor dumps one on every recovery decision (watchdog fire →
  restart, NaN streak → rollback, degrade) and the SIGTERM preemption
  handler dumps one before exit — so a fault and the recovery it
  triggered share one correlated timeline.  ``tools/blackbox_report.py``
  renders it human-readable without importing jax.
- **Chrome trace** — events also merge into ``mx.profiler``'s event
  stream via ``profiler.record_span`` (zero-duration marks for
  instants, real intervals when ``t0``/``t1`` endpoints are passed), so
  the same timeline is visible in Perfetto next to the XLA annotations.

``TPUMX_TRACING=0`` disables emission entirely: the disabled path is one
module-global check per call site (held to the same within-noise bar as
the telemetry exporter, docs/observability.md).

This module imports ONLY the stdlib at module level and is loadable
standalone (``tools/blackbox_report.py`` does) — the telemetry,
checkpoint and profiler bridges all degrade gracefully when the package
is absent.
"""
from __future__ import annotations

import json
import math
import os
import socket
import sys
import threading
import time
from collections import deque

__all__ = ["KNOWN_EVENTS", "BLACKBOX_FORMAT", "TRAIN_STEP_PHASES",
           "enabled", "configure", "emit", "set_context", "get_context",
           "snapshot", "stats", "reset", "validate_event",
           "blackbox_doc", "dump_blackbox", "blackbox_path",
           "validate_blackbox"]

BLACKBOX_FORMAT = "tpu_mx-blackbox-v1"

# The stable event-name catalog: name -> {payload field: type name}.
# Event NAMES AND FIELDS ARE AN API (docs/observability.md), statically
# checked at every emit() call site by tools/tpumx_lint.py's
# telemetry-catalog pass — keep this a literal dict so the linter can
# extract it by parsing, never importing.  Payload fields are optional
# but typed; undeclared fields are rejected at emit time.
KNOWN_EVENTS = {
    # compiled train step (tpu_mx/parallel/train_step.py): the step
    # histogram split into host-side phases (docs/observability.md
    # documents what each phase covers under the one-program step)
    "train_step.phase": {"phase": "str", "seconds": "float"},
    # fusion engine (tpu_mx/fusion.py): one event per executed flush
    "fusion.flush": {"cause": "str", "ops": "int"},
    # durability layer (tpu_mx/checkpoint.py, tpu_mx/elastic.py)
    "checkpoint.save": {"prefix": "str", "epoch": "int", "seconds": "float"},
    "checkpoint.verify": {"prefix": "str", "epoch": "int", "status": "str"},
    "checkpoint.retry": {"attempt": "int", "error": "str"},
    "checkpoint.preemption": {"signum": "int", "save_ok": "bool"},
    "elastic.resume": {"resume_from": "int"},
    "elastic.epoch_skipped": {"epoch": "int", "reason": "str"},
    # self-healing supervisor (tpu_mx/supervisor.py): every watchdog
    # fire, sentinel skip, classification and recovery decision
    "supervisor.watchdog_fire": {"name": "str", "deadline_seconds": "float"},
    "supervisor.sentinel_skip": {"loss": "float", "consecutive_bad": "int"},
    "supervisor.classify": {"kind": "str", "error": "str", "message": "str"},
    "supervisor.restart": {"n": "int", "backoff_seconds": "float",
                           "resume_epoch": "int"},
    "supervisor.rollback": {"n": "int", "resume_epoch": "int"},
    "supervisor.degrade": {"budget": "str", "error": "str"},
    "supervisor.blackbox": {"path": "str", "reason": "str"},
    # deterministic-resume capsules (tpu_mx/resume.py)
    "resume.capsule_write": {"kind": "str", "epoch": "int", "step": "int"},
    "resume.capsule_restore": {"used": "str", "epoch": "int", "step": "int",
                               "gap": "int"},
    # fault injection (tpu_mx/contrib/chaos.py): the injection and the
    # recovery it provokes share one timeline
    "chaos.inject": {"kind": "str"},
    # SDC defense plane (ISSUE 20; tpu_mx/parallel/integrity.py +
    # supervisor.py, docs/robustness.md "Silent data corruption
    # defense").  `integrity.fingerprint` records every published
    # cross-replica digest (the K-step cadence);  `integrity.vote` one
    # cohort comparison — agree=False IS the corruption verdict, with
    # `minority` the comma-joined voted-out rank(s) ("" when a tie
    # detected but could not attribute);  `integrity.quarantine` the
    # permanent eviction of a corrupt rank (never re-admitted — distinct
    # from fleet.leave/fleet.lost, which healed members survive);
    # `integrity.shadow_audit` one sampled bit-exact re-execution
    # (surface=train|decode);  `integrity.rollback` the surviving
    # majority's recovery decision, naming the last fingerprint-VERIFIED
    # step the restore is anchored to.
    "integrity.fingerprint": {"step": "int", "fp": "int", "rank": "int"},
    "integrity.vote": {"step": "int", "agree": "bool",
                       "majority_fp": "int", "minority": "str",
                       "world_size": "int"},
    "integrity.quarantine": {"rank": "int", "reason": "str",
                             "step": "int"},
    "integrity.shadow_audit": {"step": "int", "match": "bool",
                               "surface": "str"},
    "integrity.rollback": {"step": "int", "verified_step": "int",
                           "resume_epoch": "int"},
    # kvstore payload integrity (ISSUE 20): a pulled aggregate failed
    # its push-time checksum — corruption crossed the sync seam
    "kvstore.checksum_fail": {"key": "str"},
    # elastic fleet membership (tpu_mx/parallel/fleet.py + tools/launch.py
    # --supervise; docs/robustness.md "Elastic fleets").  Every membership
    # transition is on the timeline: `fleet.epoch` is the authoritative
    # record of a generation advance (who is in the world and why it
    # changed); join/leave/lost/rejoin are the per-member lifecycle;
    # `fleet.reshard` records a world-size transition driven through the
    # load_state_dict reshard seam (source=manifest for fault recovery,
    # source=live for planned scale-up from in-memory state);
    # restart_worker/degrade are the fleet supervisor's restart-budget
    # decisions.  The fleet generation is a PAYLOAD field here — the
    # trace-context `generation` field remains the supervisor's restore
    # generation.
    "fleet.epoch": {"generation": "int", "world_size": "int",
                    "reason": "str"},
    "fleet.join": {"member": "int", "generation": "int"},
    "fleet.leave": {"member": "int", "generation": "int", "reason": "str"},
    "fleet.lost": {"member": "int", "age_seconds": "float"},
    "fleet.rejoin": {"member": "int", "generation": "int"},
    "fleet.reshard": {"generation": "int", "from_world": "int",
                      "to_world": "int", "source": "str"},
    "fleet.restart_worker": {"member": "int", "n": "int",
                             "backoff_seconds": "float"},
    "fleet.degrade": {"world_size": "int", "reason": "str"},
    # fleet observability plane (ISSUE 18; tpu_mx/parallel/fleet_obs.py):
    # the windowed persistent-straggler detector's state FLIP — `rank`
    # is the attributed straggler, `excess_seconds` its mean per-step
    # excess over the fastest rank, `phase` the dominant slow phase
    # (data_wait/dispatch/loss_readback) and `steps` how many correlated
    # steps the window judged.  rank=-1 records the all-clear flip.
    "fleet.straggler": {"rank": "int", "excess_seconds": "float",
                        "phase": "str", "steps": "int"},
    # inference serving runtime (tpu_mx/serving/, docs/serving.md): the
    # request lifecycle.  Per-request events (admit/prefill/evict/reject)
    # are additionally stamped with the request-scoped `request` context
    # field (set_context(request=...) — the serving analog of the
    # training loop's step context), so a slow request's black box is
    # reconstructible; decode is batch-scoped and rides the engine-step
    # `step`/`generation` context like a train step.
    # `recovered` (ISSUE 19): True when the admission is a journal
    # recovery (scheduler.restore — gates bypassed), absent otherwise
    "serve.admit": {"request": "str", "prompt_tokens": "int",
                    "max_new_tokens": "int", "tenant": "str",
                    "recovered": "bool"},
    "serve.reject": {"request": "str", "reason": "str"},
    # `cached` (ISSUE 12): how many leading prompt tokens were served
    # from the shared-prefix index instead of computed — a prefill that
    # rode the cache attributes its speed honestly.  `replayed`
    # (ISSUE 19): how many already-committed GENERATED tokens this
    # prefill replayed in the same call — nonzero means this was a
    # restart/handoff recovery that rebuilt the stream in O(1 prefill)
    # instead of re-decoding
    "serve.prefill": {"request": "str", "tokens": "int", "seconds": "float",
                     "cached": "int", "replayed": "int"},
    "serve.decode": {"batch": "int", "tokens": "int", "seconds": "float"},
    "serve.evict": {"request": "str", "reason": "str", "generated": "int"},
    "serve.restart": {"n": "int", "reason": "str", "requeued": "int"},
    # emitted once per engine construction (so once per generation): the
    # decode-attention arm this engine resolved (dense / paged /
    # paged-kernel), where its KV pool lives (host / device), whether
    # the whole step runs as ONE fused device program (ISSUE 16) and
    # the speculative draft-window width (1 = speculation off) — a
    # restarted engine's black box records which data plane it was on
    # `sampling` (ISSUE 19): greedy or the host sampler spec — a
    # non-greedy engine pins fused off and the spec window to 1 (both
    # sample greedily/on-device and would fork the journaled stream)
    "serve.decode_path": {"path": "str", "storage": "str",
                          "sharing": "bool", "fused": "bool",
                          "spec_window": "int", "sampling": "str"},
    # graceful drain / hot handoff / degraded drain (ISSUE 19): one
    # event per admission-stopping transition — kind=drain (quiesce to
    # idle, admission closed), kind=handoff (live sessions migrated to
    # a fresh engine generation via prefill replay, no restart budget
    # spent), kind=degrade (budget exhausted: queued work failed, the
    # running batch migrated to one final generation and drained)
    "serve.drain": {"kind": "str", "inflight": "int", "pending": "int"},
    # shared-prefix index pressure eviction (ISSUE 12): one event per
    # relief pass — `released` index entries freed to satisfy a
    # `need`-block allocation (tpu_mx/serving/kv_cache.py::_alloc)
    "serve.prefix_evict": {"released": "int", "need": "int"},
    # capacity exhaustion (ISSUE 14): a genuine CacheExhausted — the
    # pool could not satisfy `need` blocks even after pressure relief.
    # `holders` counts the live ledger holders at fault time and
    # `forensic` names the rolling <prefix>-capacity.json record set
    # (empty when forensics are unarmed) that attributes every one of
    # them — rendered by tools/capacity_report.py without jax
    "serve.capacity_exhausted": {"need": "int", "free": "int",
                                 "holders": "int", "forensic": "str"},
    # per-request latency attribution (tpu_mx/serving/timeline.py,
    # ISSUE 11): emitted ONCE per request at finish/fail/reject — not
    # per phase transition, which would flood the ring — with the
    # request's wall clock decomposed into the typed phases.  The
    # invariant the serve CI tier gates: the phase fields sum to the
    # measured request latency within 5% (and the breakdown snapshot at
    # first-token time sums to the measured ttft).
    # `tenant`/`cached_tokens` (ISSUE 12): the tenant label the
    # per-tenant SLO report groups by, and the prompt tokens the final
    # attempt served from the shared-prefix cache (a cache-served
    # prefill's short `prefill` phase is attributed honestly, not
    # mistaken for noise).  NOTE for offline consumers: phase fields are
    # exactly the float fields other than latency/ttft (slo_report
    # derives them that way) — any new float here must be a phase.
    "serve.request_timeline": {
        "request": "str", "outcome": "str", "latency": "float",
        "ttft": "float", "queue_wait": "float", "prefill": "float",
        "decode_gap": "float", "restart_penalty": "float",
        "defer_stall": "float", "reject": "float",
        "tokens": "int", "requeues": "int", "defers": "int",
        "tenant": "str", "cached_tokens": "int"},
    # SLO monitor breach transitions (tpu_mx/serving/slo.py): emitted
    # when a declared target starts or stops breaching its multi-window
    # error-budget burn bar — the timeline record of WHEN the SLO state
    # flipped (the continuous state lives in the serve.slo_* gauges)
    "serve.slo": {"slo": "str", "breaching": "bool", "burn_rate": "float",
                  "estimate_seconds": "float", "attainment": "float",
                  "threshold_seconds": "float"},
}

# the documented values of train_step.phase's `phase` field (the whole
# device-side forward+backward+optimizer runs as ONE XLA program, so the
# phases are the HOST-side stations around it — docs/observability.md)
TRAIN_STEP_PHASES = ("data_wait", "recompile", "dispatch", "loss_readback",
                     "optimizer_update")

_TYPES = {"str": str, "int": int, "float": (int, float), "bool": bool}

# REENTRANT by requirement, not convenience: the SIGTERM preemption
# handler (checkpoint.PreemptionHandler) runs on the main thread between
# bytecodes and emits events + dumps a black box — if the interrupted
# frame was itself inside emit() (several per training step), a plain
# Lock would self-deadlock the whole preemption grace window
_lock = threading.RLock()
_DEFAULT_CAPACITY = 512
_ring: deque = deque(maxlen=_DEFAULT_CAPACITY)
_emitted = 0
_dropped = 0
_enabled = os.environ.get("TPUMX_TRACING", "1") != "0"

# the process-wide trace context every event is stamped with.  run_id is
# wall-clock-derived (an *identifier*, not an RNG seed — determinism
# applies to the training computation, not to forensic labels).
_context = {
    "run_id": "%s-%d-%d" % (socket.gethostname(), os.getpid(),
                            int(time.time())),
    "epoch": None,
    "step": None,
    "generation": 0,
    # request-scoped context (tpu_mx/serving/): the id of the request an
    # event belongs to, or None outside per-request work.  The serving
    # engine stamps it around admit/prefill/evict exactly like the
    # supervisor stamps epoch/step around a train step; batch-scoped
    # decode events leave it None and correlate via step/generation.
    "request": None,
    # fleet identity (ISSUE 18): this process's fleet rank and the
    # membership generation it has adopted, stamped by
    # tpu_mx/parallel/fleet.py on epoch adoption (None outside a
    # fleet).  `fleet_generation` is the MEMBERSHIP epoch — distinct
    # from `generation`, which remains the supervisor's restore
    # generation.  The cross-rank step correlation
    # (tpu_mx/parallel/fleet_obs.py) keys on (epoch, step,
    # fleet_generation) across ranks' shipped events.
    "rank": None,
    "fleet_generation": None,
}


def enabled():
    """Whether emit() records anything (``TPUMX_TRACING=0`` disables)."""
    return _enabled


def configure(enabled=None, capacity=None):
    """Adjust the recorder: ``enabled`` toggles emission, ``capacity``
    re-sizes the ring (keeping the newest events).  Returns the live
    ``(enabled, capacity)`` pair."""
    global _enabled, _ring
    with _lock:
        if enabled is not None:
            _enabled = bool(enabled)
        if capacity is not None:
            capacity = int(capacity)
            if capacity < 1:
                raise ValueError("tracing capacity must be >= 1")
            _ring = deque(_ring, maxlen=capacity)
        return _enabled, _ring.maxlen


def set_context(**fields):
    """Update the process-wide trace context (``run_id``, ``epoch``,
    ``step``, ``generation``, ``request``).  The training loop owns the
    first four: the supervisor stamps epoch/step/generation around every
    supervised step; the serving engine stamps step/generation per engine
    step and ``request`` around per-request work.  Every event emitted
    anywhere in the process — including on the watchdog daemon thread —
    carries the values current at emit time."""
    unknown = set(fields) - set(_context)
    if unknown:
        raise ValueError(f"unknown trace-context field(s) {sorted(unknown)} "
                         f"(have: {sorted(_context)})")
    with _lock:
        _context.update(fields)


def get_context():
    """A copy of the live trace context."""
    with _lock:
        return dict(_context)


# non-finite floats are encoded as these strings: strict JSON has no
# NaN/Infinity token, and a black box MUST parse in jq/browsers/any
# spec-compliant reader — a NaN loss is exactly what a divergence box
# records, so the encoding is part of the schema, not an edge case
_NONFINITE = {"nan": float("nan"), "inf": float("inf"),
              "-inf": float("-inf")}


def _check_payload(event, payload, normalize=False):
    """Shared by emit() and validate_event(): every payload field must be
    declared for `event` in :data:`KNOWN_EVENTS` with a matching type.
    ``normalize=True`` (the emit path) additionally rewrites non-finite
    floats to their string encoding so every ring record is strict-JSON
    safe; the validate path accepts either spelling."""
    decl = KNOWN_EVENTS.get(event)
    if decl is None:
        raise ValueError(f"unknown event name {event!r} — not in "
                         "tracing.KNOWN_EVENTS (stable event names are an "
                         "API; register new events in the catalog + "
                         "docs/observability.md)")
    for k, v in payload.items():
        if k not in decl:
            raise ValueError(f"{event}: undeclared payload field {k!r} "
                             f"(declared: {sorted(decl)})")
        want = _TYPES[decl[k]]
        if decl[k] == "float" and isinstance(v, str) and v in _NONFINITE:
            continue  # the strict-JSON encoding of a non-finite float
        if not isinstance(v, want) or (decl[k] != "bool"
                                       and isinstance(v, bool)):
            raise ValueError(f"{event}: payload field {k!r} must be "
                             f"{decl[k]}, got {type(v).__name__} {v!r}")
        if normalize and decl[k] == "float" \
                and not math.isfinite(float(v)):
            payload[k] = "nan" if v != v else ("inf" if v > 0 else "-inf")
    return payload


def validate_event(rec):
    """Raise ValueError unless `rec` is a schema-valid event record:
    a known ``event`` name, numeric ``ts``, the four context fields
    (``run_id`` str; ``epoch``/``step`` int or None; ``generation``
    int), and a ``data`` payload whose fields are declared — with the
    declared types — in :data:`KNOWN_EVENTS` (non-finite floats appear
    as their string encodings ``"nan"``/``"inf"``/``"-inf"``)."""
    if not isinstance(rec, dict):
        raise ValueError(f"event is {type(rec).__name__}, not an object")
    name = rec.get("event")
    if name not in KNOWN_EVENTS:
        raise ValueError(f"unknown event name {name!r} — not in "
                         "tracing.KNOWN_EVENTS (stable event names are an "
                         "API; register new events in the catalog + "
                         "docs/observability.md)")
    if not isinstance(rec.get("ts"), (int, float)) \
            or isinstance(rec.get("ts"), bool):
        raise ValueError(f"{name}: missing numeric 'ts'")
    if not isinstance(rec.get("run_id"), str) or not rec.get("run_id"):
        raise ValueError(f"{name}: missing 'run_id'")
    for field in ("epoch", "step"):
        v = rec.get(field, "missing")
        if v is not None and (not isinstance(v, int) or isinstance(v, bool)):
            raise ValueError(f"{name}: {field!r} must be int or None, "
                             f"got {v!r}")
    if not isinstance(rec.get("generation"), int) \
            or isinstance(rec.get("generation"), bool):
        raise ValueError(f"{name}: missing int 'generation'")
    # `request` joined the context with the serving runtime; events
    # recorded by older builds simply lack the key (still valid)
    req = rec.get("request")
    if req is not None and not isinstance(req, str):
        raise ValueError(f"{name}: 'request' must be str or None, "
                         f"got {req!r}")
    # `rank`/`fleet_generation` joined with the fleet observability
    # plane (ISSUE 18); same older-builds-lack-the-key rule
    for field in ("rank", "fleet_generation"):
        v = rec.get(field)
        if v is not None and (not isinstance(v, int)
                              or isinstance(v, bool)):
            raise ValueError(f"{name}: {field!r} must be int or None, "
                             f"got {v!r}")
    data = rec.get("data")
    if not isinstance(data, dict):
        raise ValueError(f"{name}: missing 'data' payload object")
    _check_payload(name, data)
    return rec


def emit(event, t0=None, t1=None, **payload):
    """Record one event into the ring buffer (no-op when disabled).

    ``payload`` fields must be declared in :data:`KNOWN_EVENTS` with
    matching types — a typo'd field or name raises immediately (and the
    lint pass catches unknown *names* statically).  ``t0``/``t1``
    (``time.perf_counter`` endpoints) additionally merge the interval
    into the profiler chrome-trace via ``profiler.record_span``; events
    without endpoints merge as zero-duration marks.  Returns the record
    (None when disabled)."""
    global _emitted, _dropped
    if not _enabled:
        return None
    decl = KNOWN_EVENTS.get(event)
    if t0 is not None and t1 is not None and decl and "seconds" in decl:
        payload.setdefault("seconds", t1 - t0)
    _check_payload(event, payload, normalize=True)
    rec = {"event": event, "ts": time.time(), "data": payload}
    with _lock:
        rec.update(_context)
        if len(_ring) == _ring.maxlen:
            _dropped += 1
        _emitted += 1
        _ring.append(rec)
    _merge_profiler(event, t0, t1, payload)
    return rec


def _merge_profiler(event, t0, t1, payload):
    """Mirror the event onto the profiler chrome-trace (one Perfetto
    timeline for events + spans + XLA).  The span name is qualified by
    the event's categorical field (``train_step.phase:dispatch``,
    ``chaos.inject:hang``, ``fusion.flush:read_barrier``) — without it
    every phase of a step would collapse into one indistinguishable
    aggregate row, defeating phase attribution.  Degrades to a no-op
    standalone (no package) or when the profiler is not recording."""
    try:
        from . import profiler
    except ImportError:
        return
    try:
        for key in ("phase", "kind", "cause"):
            v = payload.get(key)
            if isinstance(v, str):
                event = f"{event}:{v}"
                break
        if t0 is None or t1 is None:
            t0 = t1 = time.perf_counter()
        profiler.record_span(event, t0, t1, category="tracing")
    except Exception:
        pass  # profiler torn down mid-exit must not break emission


def snapshot(last=None):
    """A consistent copy of the ring's events, oldest first (``last=N``
    keeps only the newest N)."""
    with _lock:
        events = list(_ring)
    if last is not None:
        events = events[-int(last):]
    return events


def stats():
    """``{emitted, dropped, capacity, size}`` — overflow is visible,
    never silent (a black box whose window missed the fault says so)."""
    with _lock:
        return {"emitted": _emitted, "dropped": _dropped,
                "capacity": _ring.maxlen, "size": len(_ring)}


def reset():
    """Drop every event and context override (test hook); keeps run_id."""
    global _emitted, _dropped
    with _lock:
        _ring.clear()
        _emitted = 0
        _dropped = 0
        _context.update(epoch=None, step=None, generation=0, request=None,
                        rank=None, fleet_generation=None)


# ---------------------------------------------------------------------------
# the black box
# ---------------------------------------------------------------------------
def blackbox_path(prefix):
    return f"{prefix}-blackbox.json"


def _environment_fingerprint():
    """Where this process ran: enough to reproduce/attribute, nothing
    secret.  jax's version is recorded only when jax is ALREADY imported
    — a black box must be assemblable from a process that never booted
    it."""
    env = {k: v for k, v in sorted(os.environ.items())
           if k.startswith(("TPUMX_", "JAX_", "XLA_"))}
    jax_mod = sys.modules.get("jax")
    return {
        "hostname": socket.gethostname(),
        "pid": os.getpid(),
        "python": sys.version.split()[0],
        "platform": sys.platform,
        "argv": list(sys.argv),
        "cwd": os.getcwd(),
        "env": env,
        "jax": getattr(jax_mod, "__version__", None),
    }


def blackbox_doc(reason="", last=None):
    """Assemble (not persist) the black-box document: format tag, the
    trigger ``reason``, live trace context, the last N events, ring
    stats, a full telemetry snapshot and the environment fingerprint."""
    try:
        from . import telemetry
        # surface ring overflow (and any future bridge gauge) in the
        # box's own telemetry — one shared helper so the flush and
        # black-box export paths can never drift apart
        telemetry._refresh_bridge_gauges()
        tel = telemetry.snapshot()
    except ImportError:
        tel = []  # standalone module load: no telemetry registry
    return {
        "format": BLACKBOX_FORMAT,
        "reason": str(reason),
        "wall_time": time.time(),
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "context": get_context(),
        "stats": stats(),
        "events": snapshot(last=last),
        "telemetry": tel,
        "environment": _environment_fingerprint(),
    }


def dump_blackbox(prefix, reason="", last=None):
    """Persist the black box as ``<prefix>-blackbox.json`` through
    ``checkpoint.atomic_write`` (all-or-nothing: a crash mid-dump leaves
    the previous box, never a torn one) and return the path.

    The file is ROLLING — each dump overwrites the last — but the ring
    holds the full recent timeline, so the newest box still contains
    every earlier fault within the window (``stats.dropped`` says when
    the window was exceeded).  Render with ``tools/blackbox_report.py``.
    """
    path = blackbox_path(prefix)
    doc = blackbox_doc(reason=reason, last=last)
    try:
        # STRICT JSON: events are non-finite-safe by construction (emit
        # encodes NaN/Inf as strings), and a box that jq/browsers cannot
        # parse defeats the read-it-anywhere contract
        payload = json.dumps(doc, sort_keys=True, allow_nan=False)
    except ValueError:
        # a non-finite value outside the events (e.g. a telemetry
        # histogram that observed NaN): keep the box rather than lose
        # the post-mortem — python's reader accepts the NaN token
        payload = json.dumps(doc, sort_keys=True)
    try:
        from .checkpoint import atomic_write
    except ImportError:
        # standalone module load (no package → no durability layer): a
        # torn box is still parseable up to the tear worst-case, and
        # this path never runs inside the supervised stack
        # tpumx-lint: disable=durability -- degraded standalone mode
        # only; the package path below always uses atomic_write
        with open(path, "w", encoding="utf-8") as f:
            f.write(payload)
    else:
        with atomic_write(path, "w") as f:
            f.write(payload)
        try:
            from . import telemetry
            telemetry.counter("tracing.blackbox_dumps").inc()
        except ImportError:
            pass
    emit("supervisor.blackbox", path=path, reason=str(reason))
    return path


def validate_blackbox(doc):
    """Raise ValueError unless `doc` is a schema-valid black box: the
    known format tag, a complete context object, schema-valid events
    (each individually checked against :data:`KNOWN_EVENTS`), list-typed
    telemetry, and the ring stats/environment objects."""
    if not isinstance(doc, dict):
        raise ValueError(f"black box is {type(doc).__name__}, not an object")
    if doc.get("format") != BLACKBOX_FORMAT:
        raise ValueError(f"unknown black-box format {doc.get('format')!r} "
                         f"(this build reads {BLACKBOX_FORMAT})")
    ctx = doc.get("context")
    if not isinstance(ctx, dict) or \
            not {"run_id", "epoch", "step", "generation"} <= set(ctx):
        raise ValueError("black box missing a complete 'context' object "
                         "(run_id/epoch/step/generation)")
    if not isinstance(doc.get("events"), list):
        raise ValueError("black box missing the 'events' list")
    for i, rec in enumerate(doc["events"]):
        try:
            validate_event(rec)
        except ValueError as e:
            raise ValueError(f"events[{i}]: {e}") from e
    if not isinstance(doc.get("telemetry"), list):
        raise ValueError("black box missing the 'telemetry' list")
    for field in ("stats", "environment"):
        if not isinstance(doc.get(field), dict):
            raise ValueError(f"black box missing the {field!r} object")
    if not isinstance(doc.get("wall_time"), (int, float)):
        raise ValueError("black box missing numeric 'wall_time'")
    return doc
