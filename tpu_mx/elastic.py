"""Elastic-lite: multi-host failure detection + durable auto-resume
(SURVEY §5.3, docs/robustness.md).

The reference's ps-lite tracked worker liveness through the scheduler and
could re-admit workers.  A TPU SPMD job has no scheduler tier and XLA
collectives simply hang if a peer dies — so the cheap, robust design is:

1. **Failure detection** = a *timeout barrier* between training epochs (or
   every N steps): every worker calls `barrier(tag, timeout)`; if any peer
   is gone, the survivors get a clean `WorkerFailure` within the timeout
   instead of hanging forever in a collective.
2. **Recovery** = the auto-resume contract: checkpoints carry epoch numbers
   (`prefix-0007.params` ...), `latest_checkpoint(prefix)` finds the newest
   *verified* one, and a `--resume` run restarts the whole SPMD job from it.
   Re-forming the collective group is the launcher's job (just rerun it);
   re-forming *state* is this module's.

"Newest complete" is enforced, not assumed: every epoch written through
`save_checkpoint` commits a manifest (tpu_mx/checkpoint.py) as its last
write, and the resume path verifies sizes + sha256 digests before touching
model state, skipping torn/corrupt epochs and falling back to the next
good one.  Manifest-less checkpoints (pre-durability writers, bare
`net.save_parameters`) still load, with a warning.  `preemption_handler`
(re-exported from tpu_mx.checkpoint) turns SIGTERM into one emergency
durable save.  The whole path is chaos-tested: tpu_mx/contrib/chaos.py
injects mid-save crashes, torn writes and dead peers deterministically
(tests/test_elastic.py).

The barrier runs `multihost_utils.sync_global_devices` on a daemon thread
and joins with a timeout (`supervisor.run_with_deadline` — the same
watchdog the training supervisor puts around every step) — a hung
collective (dead peer) leaves a parked daemon thread behind but the main
thread gets control back, reports, and the supervisor (tpu_mx/supervisor.py)
restarts from the last verified checkpoint.
"""
from __future__ import annotations

import glob
import logging
import os
import pickle
import re
import time as _time

from .base import MXNetError
from . import checkpoint as _ckpt
from . import telemetry as _telemetry
from . import tracing as _tracing
from .checkpoint import preemption_handler  # noqa: F401  (re-export)

__all__ = ["WorkerFailure", "barrier", "latest_checkpoint",
           "candidate_checkpoints", "auto_resume", "save_checkpoint",
           "preemption_handler"]

log = logging.getLogger(__name__)


class WorkerFailure(MXNetError):
    """A peer did not reach the barrier within the timeout (died or hung)."""


def barrier(tag="tpumx_elastic", timeout=60.0, generation=None, fleet=None):
    """Synchronize all processes; raise `WorkerFailure` if the group does not
    converge within `timeout` seconds.  Single-process: no-op.

    Call between epochs (cheap: one tiny collective) so a dead rank turns
    into a clean, fast failure instead of an indefinite hang in the next
    psum.  The `kill_peer` chaos knob (contrib.chaos) makes this raise
    deterministically so recovery loops are testable single-process.

    Elastic fleets (docs/robustness.md): pass ``fleet=`` (a
    ``parallel.fleet.Fleet``) and the rendezvous is tagged with the
    membership epoch — ``tag@gen`` — so a zombie worker still holding a
    previous generation can never satisfy, or wedge, the current
    cohort's barrier: mismatched tags cannot pair, and better, the stale
    arrival is detected HERE, before the collective, and raises
    ``WorkerFailure`` loudly instead of waiting out the timeout.
    ``generation=`` alone (an int) just tags, for callers that manage
    membership themselves."""
    from .contrib import chaos
    chaos.configure_from_env()
    if chaos.peer_killed():
        raise WorkerFailure(
            f"barrier '{tag}': chaos kill_peer armed — simulating a dead "
            "peer. Restart the job with --resume to continue from the last "
            "checkpoint.")
    if fleet is not None:
        current = fleet.generation
        if generation is None:
            generation = fleet.acked_generation
        if int(generation) != int(current):
            raise WorkerFailure(
                f"barrier '{tag}': stale fleet generation {generation} "
                f"(the membership epoch is now {current}) — this worker "
                "belongs to a previous epoch and must reshard/rejoin "
                "before it may rendezvous with the current cohort")
    if generation is not None:
        tag = f"{tag}@{int(generation)}"
    import jax
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils
    # the thread-join-with-deadline lives in supervisor.run_with_deadline
    # now (the supervisor's hung-step watchdog is this same pattern); a
    # timeout raises WatchdogTimeout, a WorkerFailure subclass
    from .supervisor import run_with_deadline
    try:
        run_with_deadline(
            lambda: multihost_utils.sync_global_devices(tag),
            timeout, name=f"barrier-{tag}",
            message=(
                f"barrier '{tag}' timed out after {timeout:.0f}s: a worker "
                f"is dead or hung (rank {jax.process_index()} of "
                f"{jax.process_count()} reporting). Restart the job with "
                "--resume to continue from the last checkpoint."))
    except WorkerFailure:
        raise
    except Exception as e:  # pragma: no cover - backend-specific
        raise WorkerFailure(f"barrier '{tag}' failed: {e}")


# ≥5-digit epochs are legal: the reference's %04d format *pads to* four
# digits, it does not cap at four (a 4h-step-checkpointing run passes
# epoch 10000 in under a month)
_EPOCH_RE = re.compile(r"-(\d{4,})\.params(\.npz)?$")


def _screened_checkpoints(prefix):
    """Yield `(epoch, params_path, status)` newest-first, integrity-screened
    (status is 'verified' or 'legacy' — corrupt epochs are skipped).

    Epochs whose manifest fails verification (torn/missing/corrupt files)
    are skipped with a warning naming the damage.  Manifest-less epochs are
    *legacy* (pre-durability writers) — accepted with a warning — UNLESS
    the prefix has manifested epochs and this one is newer than the newest
    of them: then it is almost certainly a save that died between the data
    rename and the manifest commit, and trusting it would resurrect exactly
    the torn-resume failure the manifest exists to prevent, so it is
    skipped.  In-flight `*.tmp.<pid>` debris from a crashed save never
    matches."""
    found = {}
    for path in glob.glob(f"{prefix}-*.params*"):
        m = _EPOCH_RE.search(path)
        if m:
            found.setdefault(int(m.group(1)), path)
    manifested = {e for e in found
                  if os.path.exists(_ckpt.manifest_path(prefix, e))}
    newest_manifested = max(manifested) if manifested else None
    for epoch in sorted(found, reverse=True):
        status, problems = _ckpt.verify_checkpoint(prefix, epoch)
        if status == "verified":
            yield epoch, found[epoch], status
        elif status == "legacy":
            if newest_manifested is not None and epoch > newest_manifested:
                _telemetry.counter("elastic.epochs_skipped_corrupt").inc()
                _tracing.emit("elastic.epoch_skipped", epoch=epoch,
                              reason="manifest-less newer than a "
                                     "manifested epoch (interrupted save)")
                log.warning(
                    "checkpoint epoch %d of %s has no manifest although "
                    "older epochs of this prefix do: treating it as a save "
                    "interrupted before its manifest commit — skipping",
                    epoch, prefix)
                continue
            _telemetry.counter("elastic.legacy_fallbacks").inc()
            log.warning(
                "checkpoint epoch %d of %s has no manifest (legacy "
                "writer or pre-durability save): accepting unverified",
                epoch, prefix)
            yield epoch, found[epoch], status
        else:
            _telemetry.counter("elastic.epochs_skipped_corrupt").inc()
            _tracing.emit("elastic.epoch_skipped", epoch=epoch,
                          reason="; ".join(problems)[:200])
            log.warning("skipping corrupt checkpoint epoch %d of %s: %s",
                        epoch, prefix, "; ".join(problems))


def candidate_checkpoints(prefix):
    """Yield `(epoch, params_path)` newest-first, integrity-screened
    (see `_screened_checkpoints` for the screening rules)."""
    for epoch, params, _status in _screened_checkpoints(prefix):
        yield epoch, params


def latest_checkpoint(prefix):
    """Newest *verified* `(epoch, params_path)` under the reference's
    checkpoint naming (`prefix-0007.params[.npz]`), or (None, None) if no
    loadable epoch exists.  Corrupt epochs (failed manifest verification)
    are skipped in favor of the next-newest good one."""
    for epoch, params in candidate_checkpoints(prefix):
        return epoch, params
    return (None, None)


def _states_loadable(states_path):
    """Full unpickle of a trainer/module .states file WITHOUT applying it —
    the pre-commit validation that prevents a half-restore (params loaded,
    then states blow up)."""
    with open(states_path, "rb") as f:
        pickle.load(f)


def auto_resume(prefix, net=None, module=None, trainer=None):
    """Restore the newest *loadable* checkpoint for a Gluon net (or Module)
    + optional Trainer states; returns the epoch to resume FROM (0 if
    fresh).

    The `--resume` contract (SURVEY §5.3): a restarted job calls this before
    the train loop and starts at the returned epoch.  Robustness contract
    (ISSUE 2): an epoch is committed to only after (a) its manifest
    verifies — `_screened_checkpoints` — and (b) its `.states` file, when a
    trainer is passed, actually unpickles (pre-checked for *legacy* epochs;
    verified epochs' bytes are already sha256-proven, so the extra read is
    skipped); any failure falls back to the next-newest epoch instead of
    half-restoring or crashing.  If every candidate fails AFTER some
    attempt already mutated net/module/trainer state, an MXNetError is
    raised — returning 0 ('train fresh') over silently half-restored state
    would be the exact corruption this module exists to prevent."""
    mutated = False
    for epoch, params, status in _screened_checkpoints(prefix):
        _telemetry.counter("elastic.resume_attempts").inc()
        states = f"{prefix}-{epoch:04d}.states"
        have_states = os.path.exists(states)
        if trainer is not None and have_states and status == "legacy":
            try:
                _states_loadable(states)
            except Exception as e:
                log.warning(
                    "epoch %d: %s exists but does not unpickle (%s: %s) — "
                    "falling back a checkpoint instead of half-restoring",
                    epoch, states, type(e).__name__, e)
                continue
        try:
            if net is not None:
                net.load_parameters(params)
                mutated = True
            if module is not None:
                sym, arg, aux = __import__("tpu_mx").model.load_checkpoint(
                    prefix, epoch)
                module.set_params(arg, aux)
                mutated = True
        except Exception as e:
            log.warning("epoch %d: params failed to load (%s: %s) — "
                        "falling back a checkpoint", epoch,
                        type(e).__name__, e)
            continue
        if trainer is not None and have_states:
            try:
                trainer.load_states(states)
            except Exception as e:
                # unpickled fine but failed to APPLY (format drift, wrong
                # optimizer/param set): fall back — the next iteration's
                # param load overwrites the partial restore
                log.warning(
                    "epoch %d: %s unpickled but failed to apply "
                    "(%s: %s) — falling back a checkpoint", epoch, states,
                    type(e).__name__, e)
                continue
        _tracing.emit("elastic.resume", resume_from=epoch + 1)
        return epoch + 1
    if mutated:
        raise MXNetError(
            f"auto_resume({prefix!r}): every candidate epoch failed, and a "
            "failed attempt already modified net/module/trainer state — "
            "re-initialize before training fresh (state is a partial mix, "
            "not epoch-0)")
    return 0


def save_checkpoint(prefix, epoch, net=None, trainer=None, keep_last=None,
                    attempts=4, capsule=None):
    """Durable counterpart of `auto_resume`: write the epoch's params (and
    trainer states) atomically, commit the manifest LAST, then apply
    retention.

    Every write is atomic (tmp+fsync+rename) and wrapped in
    `checkpoint.retry` against transient filesystem errors; the manifest is
    the commit point, so a crash anywhere mid-save leaves the previous
    epoch as the newest *verified* checkpoint.  `keep_last=K` prunes older
    epochs (never the newest verified one).  Returns the params path.

    `capsule=` (a `resume.CapsuleManager`) additionally commits the
    epoch's training-state capsule — RNG streams + data-iterator cursors
    (docs/robustness.md "Deterministic resume") — INSIDE the manifest, so
    the capsule is size+sha256 verified with the checkpoint it belongs to.

    Module users: `module.save_checkpoint(prefix, epoch)` commits its own
    manifest through `model.save_checkpoint` — this helper is the Gluon
    (net/trainer) flow, and the natural `save_fn` for
    `preemption_handler`."""
    if net is None and trainer is None:
        raise MXNetError("save_checkpoint: pass net= and/or trainer=")
    t_save = _time.perf_counter()
    with _telemetry.span("checkpoint.save_seconds"):
        files = []
        params = f"{prefix}-{epoch:04d}.params"
        if net is not None:
            _ckpt.retry(lambda: net.save_parameters(params),
                        attempts=attempts)
            files.append(params)
        if trainer is not None:
            states = f"{prefix}-{epoch:04d}.states"
            _ckpt.retry(lambda: trainer.save_states(states),
                        attempts=attempts)
            files.append(states)
        if capsule is not None:
            files.append(_ckpt.retry(
                lambda: capsule.write_epoch_file(epoch), attempts=attempts))
        _ckpt.retry(lambda: _ckpt.write_manifest(prefix, epoch, files),
                    attempts=attempts)
        if keep_last:
            # the epoch just committed is verified by construction — skip
            # the full from-disk re-hash the newest-verified scan would
            # otherwise do
            _ckpt.apply_retention(prefix, keep_last, known_verified=epoch)
        _tracing.emit("checkpoint.save", t0=t_save, t1=_time.perf_counter(),
                      prefix=os.path.basename(str(prefix)),
                      epoch=int(epoch))
        return params
