"""Elastic-lite: multi-host failure detection + auto-resume (SURVEY §5.3).

The reference's ps-lite tracked worker liveness through the scheduler and
could re-admit workers.  A TPU SPMD job has no scheduler tier and XLA
collectives simply hang if a peer dies — so the cheap, robust design is:

1. **Failure detection** = a *timeout barrier* between training epochs (or
   every N steps): every worker calls `barrier(tag, timeout)`; if any peer
   is gone, the survivors get a clean `WorkerFailure` within the timeout
   instead of hanging forever in a collective.
2. **Recovery** = the auto-resume contract: checkpoints carry epoch numbers
   (`prefix-0007.params` ...), `latest_checkpoint(prefix)` finds the newest
   complete one, and a `--resume` run restarts the whole SPMD job from it.
   Re-forming the collective group is the launcher's job (just rerun it);
   re-forming *state* is this module's.

The barrier runs `multihost_utils.sync_global_devices` on a daemon thread
and joins with a timeout — a hung collective (dead peer) leaves a parked
daemon thread behind but the main thread gets control back, reports, and
can exit for the supervisor to restart.
"""
from __future__ import annotations

import glob
import os
import re
import threading

from .base import MXNetError

__all__ = ["WorkerFailure", "barrier", "latest_checkpoint", "auto_resume"]


class WorkerFailure(MXNetError):
    """A peer did not reach the barrier within the timeout (died or hung)."""


def barrier(tag="tpumx_elastic", timeout=60.0):
    """Synchronize all processes; raise `WorkerFailure` if the group does not
    converge within `timeout` seconds.  Single-process: no-op.

    Call between epochs (cheap: one tiny collective) so a dead rank turns
    into a clean, fast failure instead of an indefinite hang in the next
    psum."""
    import jax
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    done = threading.Event()
    err = []

    def _sync():
        try:
            multihost_utils.sync_global_devices(tag)
        except Exception as e:  # pragma: no cover - backend-specific
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=_sync, daemon=True, name=f"barrier-{tag}")
    t.start()
    if not done.wait(timeout):
        raise WorkerFailure(
            f"barrier '{tag}' timed out after {timeout:.0f}s: a worker is "
            f"dead or hung (rank {jax.process_index()} of "
            f"{jax.process_count()} reporting). Restart the job with "
            "--resume to continue from the last checkpoint.")
    if err:
        raise WorkerFailure(f"barrier '{tag}' failed: {err[0]}")


_EPOCH_RE = re.compile(r"-(\d{4})\.params(\.npz)?$")


def latest_checkpoint(prefix):
    """Newest `(epoch, params_path)` under the reference's checkpoint naming
    (`prefix-0007.params[.npz]`), or (None, None) if none exist."""
    best = (None, None)
    for path in glob.glob(f"{prefix}-*.params*"):
        m = _EPOCH_RE.search(path)
        if m:
            epoch = int(m.group(1))
            if best[0] is None or epoch > best[0]:
                best = (epoch, path)
    return best


def auto_resume(prefix, net=None, module=None, trainer=None):
    """Restore the newest checkpoint for a Gluon net (or Module) + optional
    Trainer states; returns the epoch to resume FROM (0 if fresh).

    The `--resume` contract (SURVEY §5.3): a restarted job calls this before
    the train loop and starts at the returned epoch."""
    epoch, params = latest_checkpoint(prefix)
    if epoch is None:
        return 0
    if net is not None:
        net.load_parameters(params)
    if module is not None:
        sym, arg, aux = __import__("tpu_mx").model.load_checkpoint(
            prefix, epoch)
        module.set_params(arg, aux)
    if trainer is not None:
        states = f"{prefix}-{epoch:04d}.states"
        if os.path.exists(states):
            trainer.load_states(states)
    return epoch + 1
