"""Unified runtime telemetry: a process-wide metrics registry + event spans.

The fusion engine (ISSUE 1) and the durability layer (ISSUE 2) are both
workload-dependent — "Operator Fusion in XLA" (arxiv 2301.13062) shows
fusion behavior must be *measured*, not assumed, and a recompile storm or
a checkpoint-retry spiral is invisible until something exports a number.
This module is the one place every runtime subsystem reports to:

- **Registry**: :func:`counter` / :func:`gauge` / :func:`histogram`
  create-or-fetch named metrics (optional key=value labels make distinct
  series, e.g. ``counter("chaos.injections", kind="torn_write")``).  All
  operations are thread-safe; instrumented hot paths touch the registry
  at *flush/step/save* granularity, never per-op, so the disabled-exporter
  overhead is a few dict ops per event.
- **Spans**: ``with span("elastic.save_checkpoint_seconds"): ...`` times a
  region into the same-named histogram AND — when ``mx.profiler`` is
  recording — merges the interval into the profiler's chrome-trace event
  stream, so telemetry spans land on the same Perfetto timeline as the
  XLA annotations (`profiler.record_span` is the merge point).
- **Exporters** (all pull-based; none require a server):

  1. JSONL append — set ``TPUMX_TELEMETRY=/path/metrics.jsonl`` and call
     :func:`flush` (the instrumented train loop does; an atexit hook
     writes the final snapshot).  Each flush appends one record per live
     metric (see :func:`validate_record` for the schema).  The *final*
     snapshot (``flush(final=True)`` / atexit) rewrites the whole file
     through ``checkpoint.atomic_write`` so a crash mid-dump cannot leave
     a truncated file.
  2. Prometheus text exposition — :func:`exposition` returns the
     registry in the text format a Prometheus scraper (or a human) parses;
     no HTTP server required, wire it to whatever transport exists.
  3. Chrome trace — spans ride ``mx.profiler``'s event stream (above).

Metric NAMES ARE AN API (tools/ci.py's ``obs`` tier fails on names
outside :data:`KNOWN_METRICS`); the catalog lives in
docs/observability.md.  Histograms use fixed log-scale latency buckets
(10µs→30s in 1–3–10 steps) so snapshots from different runs always merge.

This module deliberately imports ONLY the stdlib at module level: it is
imported by the lowest layers (chaos, checkpoint, fusion) and is also
loadable standalone (tools/telemetry_report.py) without booting jax.
"""
from __future__ import annotations

import atexit
import json
import os
import re
import threading
import time

__all__ = ["counter", "gauge", "histogram", "span", "get", "reset",
           "snapshot", "flush", "exposition", "validate_record",
           "configured_path", "Counter", "Gauge", "Histogram",
           "KNOWN_METRICS", "LATENCY_BUCKETS", "SEGMENT_OPS_BUCKETS"]

# fixed log-scale latency buckets, in SECONDS: 10µs → 30s in 1–3–10 steps
# (the "ms buckets": every decade of the millisecond range is covered).
# Fixed — never derived from data — so histograms from any two runs merge.
LATENCY_BUCKETS = (1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2,
                   0.1, 0.3, 1.0, 3.0, 10.0, 30.0)

# count-valued buckets for fusion segment lengths (power-of-two edges)
SEGMENT_OPS_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)

# The stable metric-name catalog (docs/observability.md).  tools/ci.py's
# `obs` tier fails the build when an emitted record's name is not listed
# here — an accidental rename breaks every dashboard reading the old name.
KNOWN_METRICS = frozenset({
    # fusion engine (tpu_mx/fusion.py)
    "fusion.flushes", "fusion.flush_cause", "fusion.segment_ops",
    "fusion.ops_fused", "fusion.segments_dead",
    "fusion.cache_hits", "fusion.cache_misses", "fusion.eager_fallbacks",
    # durability layer (tpu_mx/checkpoint.py; save_seconds is the span at
    # the whole-checkpoint save sites, write_seconds the per-file commit)
    "checkpoint.save_seconds", "checkpoint.write_seconds",
    "checkpoint.verify_seconds", "checkpoint.atomic_writes",
    "checkpoint.retries", "checkpoint.corrupt_detected",
    # elastic resume (tpu_mx/elastic.py)
    "elastic.resume_attempts", "elastic.epochs_skipped_corrupt",
    "elastic.legacy_fallbacks",
    # compiled train step (tpu_mx/parallel/train_step.py)
    "train_step.seconds", "train_step.steps", "train_step.recompiles",
    "train_step.examples_per_sec",
    # kvstore eager path (tpu_mx/kvstore.py)
    "kvstore.pushes", "kvstore.pulls",
    "kvstore.push_bytes", "kvstore.pull_bytes",
    # self-healing supervisor (tpu_mx/supervisor.py)
    "supervisor.restarts", "supervisor.rollbacks",
    "supervisor.batches_skipped", "supervisor.watchdog_fires",
    "supervisor.degraded",
    # deterministic-resume capsules (tpu_mx/resume.py; resume_step_gap is
    # the batches a recovery could NOT replay exactly — 0 under capsules,
    # and the soak CI tier fails if it is ever nonzero)
    "resume.capsules_written", "resume.capsule_restore_seconds",
    "resume.resume_step_gap",
    # fault injection (tpu_mx/contrib/chaos.py)
    "chaos.injections",
    # flight recorder (tpu_mx/tracing.py; event NAMES live in its own
    # KNOWN_EVENTS catalog — this counts black boxes persisted)
    "tracing.blackbox_dumps",
    # inference serving runtime (tpu_mx/serving/; docs/serving.md).  The
    # SLO pair: ttft = submit→first token (queueing + prefill), itl = the
    # gap between consecutive generated tokens — p50/p99 read off the
    # fixed latency buckets.  requests{state} counts every admission
    # outcome (admitted/rejected/completed/requeued); decode_steps and
    # generated_tokens are the throughput numerators; queue_depth /
    # cache_utilization are the backpressure observables.
    "serve.ttft_seconds", "serve.itl_seconds",
    "serve.tokens_per_sec", "serve.queue_depth", "serve.cache_utilization",
    "serve.requests", "serve.engine_restarts",
    "serve.decode_steps", "serve.generated_tokens",
    # decode data plane (ISSUE 9): which attention arm each call took
    # (kind=dense/paged/paged-kernel) and whether the KV block pool is
    # device-resident (1.0) or host numpy (0.0)
    "serve.decode_attention", "serve.pool_device_resident",
    # module-API training (tpu_mx/callback.py)
    "speedometer.samples_per_sec",
})

_lock = threading.RLock()
_metrics: dict = {}          # (name, labels_tuple) -> metric object


def _labels_key(labels):
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    __slots__ = ("name", "labels")
    kind = None

    def __init__(self, name, labels):
        self.name = name
        self.labels = labels


class Counter(_Metric):
    """Monotonically increasing count (resets only with the process)."""

    __slots__ = ("value",)
    kind = "counter"

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self.value = 0

    def inc(self, n=1):
        with _lock:
            self.value += n
        return self

    def _record(self, ts):
        return _rec(self, ts, self.value)


class Gauge(_Metric):
    """Last-written value (e.g. examples/sec)."""

    __slots__ = ("value",)
    kind = "gauge"

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self.value = 0.0

    def set(self, value):
        with _lock:
            self.value = float(value)
        return self

    def _record(self, ts):
        return _rec(self, ts, self.value)


class Histogram(_Metric):
    """Fixed-bucket distribution; default buckets are the log-scale
    latency ladder (:data:`LATENCY_BUCKETS`, seconds).  Tracks count, sum,
    min and max alongside the cumulative bucket counts.  ``unit`` rides
    the JSONL record so renderers know whether ms-scaling applies."""

    __slots__ = ("buckets", "counts", "count", "sum", "min", "max", "unit")
    kind = "histogram"

    def __init__(self, name, labels, buckets=None, unit="seconds"):
        super().__init__(name, labels)
        self.unit = unit
        self.buckets = tuple(float(b) for b in (buckets or LATENCY_BUCKETS))
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf overflow
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, value):
        value = float(value)
        with _lock:
            i = 0
            for b in self.buckets:
                if value <= b:
                    break
                i += 1
            self.counts[i] += 1
            self.count += 1
            self.sum += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
        return self

    def cumulative(self):
        """[(upper_bound | "+Inf", cumulative_count), ...] — monotone."""
        out, acc = [], 0
        with _lock:
            for b, c in zip(self.buckets, self.counts):
                acc += c
                out.append((b, acc))
            out.append(("+Inf", acc + self.counts[-1]))
        return out

    def _record(self, ts):
        rec = _rec(self, ts, self.count)
        rec["sum"] = self.sum
        rec["unit"] = self.unit
        if self.count:
            rec["min"] = self.min
            rec["max"] = self.max
        rec["buckets"] = [[b, c] for b, c in self.cumulative()]
        return rec


def _rec(metric, ts, value):
    rec = {"name": metric.name, "type": metric.kind, "value": value,
           "ts": ts}
    if metric.labels:
        rec["labels"] = dict(metric.labels)
    return rec


def _get_or_make(cls, name, labels, **kw):
    key = (name, _labels_key(labels))
    with _lock:
        m = _metrics.get(key)
        if m is None:
            m = _metrics[key] = cls(name, _labels_key(labels), **kw)
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}, "
                f"not {cls.kind}")
        return m


def counter(name, **labels):
    """Create-or-fetch the Counter `name` (labels make distinct series)."""
    return _get_or_make(Counter, name, labels)


def gauge(name, **labels):
    """Create-or-fetch the Gauge `name`."""
    return _get_or_make(Gauge, name, labels)


def histogram(name, buckets=None, unit="seconds", **labels):
    """Create-or-fetch the Histogram `name`; `buckets` and `unit` only
    apply on first creation (fixed thereafter — merged snapshots depend
    on the bucket edges)."""
    return _get_or_make(Histogram, name, labels, buckets=buckets, unit=unit)


def get(name, **labels):
    """The already-registered metric, or None (no create side effect)."""
    with _lock:
        return _metrics.get((name, _labels_key(labels)))


def reset():
    """Drop every metric (test hook)."""
    with _lock:
        _metrics.clear()
    _finalized.clear()


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
class span:
    """Context manager: time a region into the histogram `name` and merge
    the interval into ``mx.profiler``'s chrome-trace stream when the
    profiler is recording (one Perfetto timeline for spans + XLA)."""

    __slots__ = ("name", "labels", "_t0")

    def __init__(self, name, **labels):
        self.name = name
        self.labels = labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        histogram(self.name, **self.labels).observe(t1 - self._t0)
        try:
            from . import profiler
            profiler.record_span(self.name, self._t0, t1)
        except Exception:
            pass  # standalone load (no package) or profiler torn down
        return False


# ---------------------------------------------------------------------------
# JSONL exporter
# ---------------------------------------------------------------------------
def configured_path():
    """The JSONL sink from the TPUMX_TELEMETRY env var, or None."""
    return os.environ.get("TPUMX_TELEMETRY") or None


def snapshot():
    """One record per live metric, sharing a wall-clock ``ts``.

    Built entirely under the registry lock (no I/O happens here): a
    concurrent ``observe()`` between reading ``count`` and the bucket
    array would otherwise produce a record violating the schema's own
    +Inf-count == value invariant."""
    ts = time.time()
    with _lock:
        return [m._record(ts) for m in _metrics.values()]


def flush(path=None, final=False):
    """Append one snapshot to the JSONL sink (`path` or TPUMX_TELEMETRY).

    No sink configured → no-op (returns None), which is what makes
    instrumentation free to call this unconditionally.  ``final=True``
    rewrites the file — full history + this snapshot — through
    ``checkpoint.atomic_write``, so the at-exit dump can never leave a
    truncated file; intermediate flushes are plain appends (cheap, and a
    torn tail there is recoverable line-by-line).  Returns the records."""
    path = path or configured_path()
    if not path:
        return None
    recs = snapshot()
    payload = "".join(json.dumps(r, sort_keys=True) + "\n" for r in recs)
    # The registry _lock is NEVER held across file I/O: the write path
    # below re-enters instrumented code (atomic_write counts itself;
    # chaos faults count their own firing), and holding _lock here would
    # invert against the locks those layers hold (cfg.lock -> _lock vs
    # _lock -> cfg.lock).  _flush_io_lock serializes concurrent flush()
    # calls instead, so a final read-modify-rewrite cannot drop a
    # concurrent append.  Earlier snapshots are re-read from disk for the
    # final rewrite — no in-memory history accumulates over a long run.
    with _flush_io_lock:
        if final:
            _finalized.add(path)
            try:
                with open(path, encoding="utf-8") as f:
                    prev = f.read()
            except OSError:
                prev = ""
            try:
                from .checkpoint import atomic_write
                with atomic_write(path, "w") as f:
                    f.write(prev + payload)
            except ImportError:  # standalone module load: plain rewrite
                # tpumx-lint: disable=durability -- degraded mode only:
                # this module is loadable WITHOUT the package (no
                # checkpoint layer to import); a torn JSONL tail is
                # recoverable line-by-line
                with open(path, "w", encoding="utf-8") as f:
                    f.write(prev + payload)
        else:
            with open(path, "a", encoding="utf-8") as f:
                f.write(payload)
    return recs


# paths a final flush already rewrote — the atexit hook must not append a
# duplicate final snapshot after an explicit flush(final=True)
_finalized: set = set()
_flush_io_lock = threading.Lock()


@atexit.register
def _flush_at_exit():  # pragma: no cover — exercised via subprocess (ci obs)
    try:
        path = configured_path()
        if path and _metrics and path not in _finalized:
            flush(final=True)
    except Exception:
        pass


def validate_record(rec):
    """Raise ValueError unless `rec` is a schema-valid telemetry record.

    Schema (the contract tools/ci.py's `obs` tier enforces): every record
    has a str ``name``, ``type`` in {counter, gauge, histogram}, numeric
    ``value`` and ``ts``; histograms additionally carry a numeric ``sum``
    and cumulative ``buckets`` [[bound, count], ...] whose counts are
    monotone non-decreasing, whose last bound is "+Inf", and whose total
    equals ``value``."""
    if not isinstance(rec, dict):
        raise ValueError(f"record is {type(rec).__name__}, not an object")
    name = rec.get("name")
    if not isinstance(name, str) or not name:
        raise ValueError(f"record missing name: {rec!r}")
    kind = rec.get("type")
    if kind not in ("counter", "gauge", "histogram"):
        raise ValueError(f"{name}: bad type {kind!r}")
    for field in ("value", "ts"):
        if not isinstance(rec.get(field), (int, float)) \
                or isinstance(rec.get(field), bool):
            raise ValueError(f"{name}: missing numeric {field!r}")
    if "labels" in rec and not (
            isinstance(rec["labels"], dict)
            and all(isinstance(k, str) and isinstance(v, str)
                    for k, v in rec["labels"].items())):
        raise ValueError(f"{name}: labels must be a str->str object")
    if kind == "histogram":
        if not isinstance(rec.get("sum"), (int, float)):
            raise ValueError(f"{name}: histogram missing numeric 'sum'")
        buckets = rec.get("buckets")
        if not isinstance(buckets, list) or not buckets:
            raise ValueError(f"{name}: histogram missing 'buckets'")
        prev = None
        for entry in buckets:
            if (not isinstance(entry, list) or len(entry) != 2
                    or not isinstance(entry[1], int)):
                raise ValueError(f"{name}: malformed bucket {entry!r}")
            if prev is not None and entry[1] < prev:
                raise ValueError(
                    f"{name}: bucket counts not monotone "
                    f"({entry[1]} after {prev})")
            prev = entry[1]
        if buckets[-1][0] != "+Inf":
            raise ValueError(f"{name}: last bucket bound must be '+Inf', "
                             f"got {buckets[-1][0]!r}")
        if buckets[-1][1] != rec["value"]:
            raise ValueError(
                f"{name}: +Inf bucket count {buckets[-1][1]} != "
                f"value {rec['value']}")
    return rec


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name):
    return "tpumx_" + _NAME_RE.sub("_", name)


def _prom_labels(pairs):
    if not pairs:
        return ""
    body = ",".join(
        '%s="%s"' % (_NAME_RE.sub("_", k),
                     str(v).replace("\\", r"\\").replace('"', r'\"'))
        for k, v in pairs)
    return "{" + body + "}"


def _prom_num(v):
    return repr(float(v)) if isinstance(v, float) else str(v)


def exposition():
    """The registry in Prometheus text exposition format (one string —
    serve it over whatever transport exists; no HTTP server here).
    Counters get the conventional ``_total`` suffix; histograms emit the
    ``_bucket{le=...}`` / ``_sum`` / ``_count`` family.  Rendered under
    the registry lock (pure string building, no I/O) so a concurrent
    ``observe()`` cannot tear a histogram's bucket/sum/count family."""
    with _lock:
        return _exposition_locked()


def _exposition_locked():
    metrics = sorted(_metrics.values(), key=lambda m: (m.name, m.labels))
    lines = []
    typed = set()

    def type_line(pname, kind):
        if pname not in typed:
            typed.add(pname)
            lines.append(f"# TYPE {pname} {kind}")

    for m in metrics:
        if m.kind == "counter":
            pname = _prom_name(m.name) + "_total"
            type_line(pname, "counter")
            lines.append(f"{pname}{_prom_labels(m.labels)} "
                         f"{_prom_num(m.value)}")
        elif m.kind == "gauge":
            pname = _prom_name(m.name)
            type_line(pname, "gauge")
            lines.append(f"{pname}{_prom_labels(m.labels)} "
                         f"{_prom_num(m.value)}")
        else:
            pname = _prom_name(m.name)
            type_line(pname, "histogram")
            for bound, cum in m.cumulative():
                le = "+Inf" if bound == "+Inf" else repr(float(bound))
                lab = _prom_labels(tuple(m.labels) + (("le", le),))
                lines.append(f"{pname}_bucket{lab} {cum}")
            lines.append(f"{pname}_sum{_prom_labels(m.labels)} "
                         f"{_prom_num(m.sum)}")
            lines.append(f"{pname}_count{_prom_labels(m.labels)} "
                         f"{m.count}")
    return "\n".join(lines) + ("\n" if lines else "")
