"""Unified runtime telemetry: a process-wide metrics registry + event spans.

The fusion engine (ISSUE 1) and the durability layer (ISSUE 2) are both
workload-dependent — "Operator Fusion in XLA" (arxiv 2301.13062) shows
fusion behavior must be *measured*, not assumed, and a recompile storm or
a checkpoint-retry spiral is invisible until something exports a number.
This module is the one place every runtime subsystem reports to:

- **Registry**: :func:`counter` / :func:`gauge` / :func:`histogram`
  create-or-fetch named metrics (optional key=value labels make distinct
  series, e.g. ``counter("chaos.injections", kind="torn_write")``).  All
  operations are thread-safe; instrumented hot paths touch the registry
  at *flush/step/save* granularity, never per-op, so the disabled-exporter
  overhead is a few dict ops per event.
- **Spans**: ``with span("elastic.save_checkpoint_seconds"): ...`` times a
  region into the same-named histogram AND — when ``mx.profiler`` is
  recording — merges the interval into the profiler's chrome-trace event
  stream, so telemetry spans land on the same Perfetto timeline as the
  XLA annotations (`profiler.record_span` is the merge point).
- **Sliding windows**: every counter/histogram also keeps a ring of
  subwindow slots covering the trailing :data:`WINDOW_SECONDS`, so
  "p99 over the last minute" (``window_quantile``), SLO attainment
  (``window_fraction_le``) and windowed rates (``window_rate``) are
  O(subwindows × buckets) reads with bounded memory — the live-SLO
  layer (tpu_mx/serving/slo.py) and tools/slo_report.py sit on this.
  Window state rides each JSONL record as a ``window`` sub-object.
- **Exporters** (all pull-based; none require a server):

  1. JSONL append — set ``TPUMX_TELEMETRY=/path/metrics.jsonl`` and call
     :func:`flush` (the instrumented train loop does; an atexit hook
     writes the final snapshot).  Each flush appends one record per live
     metric (see :func:`validate_record` for the schema).  The *final*
     snapshot (``flush(final=True)`` / atexit) rewrites the whole file
     through ``checkpoint.atomic_write`` so a crash mid-dump cannot leave
     a truncated file.
  2. Prometheus text exposition — :func:`exposition` returns the
     registry in the text format a Prometheus scraper (or a human) parses;
     no HTTP server required, wire it to whatever transport exists.
  3. Chrome trace — spans ride ``mx.profiler``'s event stream (above).

Metric NAMES ARE AN API (tools/ci.py's ``obs`` tier fails on names
outside :data:`KNOWN_METRICS`); the catalog lives in
docs/observability.md.  Histograms use fixed log-scale latency buckets
(10µs→30s in 1–3–10 steps) so snapshots from different runs always merge.

This module deliberately imports ONLY the stdlib at module level: it is
imported by the lowest layers (chaos, checkpoint, fusion) and is also
loadable standalone (tools/telemetry_report.py) without booting jax.
"""
from __future__ import annotations

import atexit
import json
import math
import os
import re
import sys
import threading
import time
from bisect import bisect_left

__all__ = ["counter", "gauge", "histogram", "span", "get", "reset",
           "snapshot", "flush", "exposition", "validate_record",
           "set_fleet_identity", "fleet_identity",
           "configured_path", "Counter", "Gauge", "Histogram",
           "KNOWN_METRICS", "LATENCY_BUCKETS", "SEGMENT_OPS_BUCKETS",
           "SLO_LATENCY_BUCKETS", "WINDOW_SECONDS", "WINDOW_SUBWINDOWS",
           "quantile_from_cumulative", "fraction_le_from_cumulative",
           "parse_slo_spec", "DEFAULT_SLOS", "ATTRIBUTION_TOLERANCE"]

# fixed log-scale latency buckets, in SECONDS: 10µs → 30s in 1–3–10 steps
# (the "ms buckets": every decade of the millisecond range is covered).
# Fixed — never derived from data — so histograms from any two runs merge.
LATENCY_BUCKETS = (1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2,
                   0.1, 0.3, 1.0, 3.0, 10.0, 30.0)

# count-valued buckets for fusion segment lengths (power-of-two edges)
SEGMENT_OPS_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)


def _geometric_ladder(lo, hi, ratio):
    out, v = [], float(lo)
    while v < hi:
        out.append(round(v, 12))
        v *= ratio
    out.append(float(hi))
    return tuple(out)


# The SLO ladder: a denser fixed geometric grid (ratio 1.05, ~306 edges
# over the same 10µs→30s span) for the serving latency histograms.  The
# 1–3–10 ladder is fine for dashboards but a 3× bucket cannot support a
# "p99 within 10% of exact" claim.  The bucket-merge estimate is
# guaranteed within ONE bucket of the exact percentile, and a sparse
# tail (p99 of a 64-request trace rides its top two order statistics)
# realizes that worst case — so the ratio is sized to make one bucket
# ≈ ±5%, keeping the bench serve leg's 10% live-vs-exact bar honest
# rather than lucky.  ~2.5 KB of ints per histogram series; observe
# cost is one bisect (9 compares).  Fixed like every other ladder
# (derived from a formula, never from data) so any two runs' snapshots
# merge.
SLO_LATENCY_BUCKETS = _geometric_ladder(1e-5, 30.0, 1.05)

# Sliding-window defaults: every Counter/Histogram additionally keeps a
# ring of subwindows covering the trailing WINDOW_SECONDS, so "p99 over
# the last minute" is an O(buckets) read with bounded memory
# (subwindows × buckets ints per histogram).  configure_window() resizes
# a metric's ring (resetting its window contents, never the cumulative
# state).
WINDOW_SECONDS = 60.0
WINDOW_SUBWINDOWS = 15

# Per-name bucket defaults, applied when histogram() is called without
# explicit buckets — every creation site agrees on the edges without
# repeating them (first-creation-wins would otherwise make the edges
# depend on call order).
_DEFAULT_BUCKETS = {
    "serve.ttft_seconds": SLO_LATENCY_BUCKETS,
    "serve.itl_seconds": SLO_LATENCY_BUCKETS,
    "serve.phase_seconds": SLO_LATENCY_BUCKETS,
}

# The stable metric-name catalog (docs/observability.md).  tools/ci.py's
# `obs` tier fails the build when an emitted record's name is not listed
# here — an accidental rename breaks every dashboard reading the old name.
KNOWN_METRICS = frozenset({
    # fusion engine (tpu_mx/fusion.py)
    "fusion.flushes", "fusion.flush_cause", "fusion.segment_ops",
    "fusion.ops_fused", "fusion.segments_dead",
    "fusion.cache_hits", "fusion.cache_misses", "fusion.eager_fallbacks",
    # durability layer (tpu_mx/checkpoint.py; save_seconds is the span at
    # the whole-checkpoint save sites, write_seconds the per-file commit)
    "checkpoint.save_seconds", "checkpoint.write_seconds",
    "checkpoint.verify_seconds", "checkpoint.atomic_writes",
    "checkpoint.retries", "checkpoint.corrupt_detected",
    # elastic resume (tpu_mx/elastic.py)
    "elastic.resume_attempts", "elastic.epochs_skipped_corrupt",
    "elastic.legacy_fallbacks",
    # compiled train step (tpu_mx/parallel/train_step.py)
    "train_step.seconds", "train_step.steps", "train_step.recompiles",
    "train_step.examples_per_sec",
    # kvstore eager path (tpu_mx/kvstore.py).  checksums counts payload
    # digests recorded at push time, checksum_failures the pulls whose
    # aggregate no longer matched — silent corruption crossing the sync
    # seam, raised loudly as kvstore.IntegrityError (ISSUE 20)
    "kvstore.pushes", "kvstore.pulls",
    "kvstore.push_bytes", "kvstore.pull_bytes",
    "kvstore.checksums", "kvstore.checksum_failures",
    # self-healing supervisor (tpu_mx/supervisor.py; corruptions counts
    # DataCorruption verdicts the classify discipline handled)
    "supervisor.restarts", "supervisor.rollbacks",
    "supervisor.corruptions",
    "supervisor.batches_skipped", "supervisor.watchdog_fires",
    "supervisor.degraded",
    # SDC defense plane (ISSUE 20; tpu_mx/parallel/integrity.py,
    # docs/robustness.md "Silent data corruption defense").
    # fingerprints counts published cross-replica digests, votes the
    # cohort comparisons, mismatches the disagreeing votes (corruption
    # verdicts); verified_step is a gauge: the newest step PROVEN clean
    # by an all-agree vote (the rollback anchor, carried by the
    # capsule).  shadow_audits / shadow_mismatches count sampled
    # bit-exact re-executions and their failures (the dp=1 detector);
    # self_checks / self_check_mismatches are the serving decode twin;
    # quarantined counts ranks permanently barred by a corruption
    # verdict (fleet.quarantine — never re-admitted).
    "integrity.fingerprints", "integrity.votes", "integrity.mismatches",
    "integrity.verified_step",
    "integrity.shadow_audits", "integrity.shadow_mismatches",
    "integrity.self_checks", "integrity.self_check_mismatches",
    "integrity.quarantined",
    # deterministic-resume capsules (tpu_mx/resume.py; resume_step_gap is
    # the batches a recovery could NOT replay exactly — 0 under capsules,
    # and the soak CI tier fails if it is ever nonzero)
    "resume.capsules_written", "resume.capsule_restore_seconds",
    "resume.resume_step_gap",
    # fault injection (tpu_mx/contrib/chaos.py)
    "chaos.injections",
    # elastic fleet membership (tpu_mx/parallel/fleet.py + tools/launch.py
    # --supervise; docs/robustness.md "Elastic fleets").  membership_epoch
    # is the monotone fleet generation this process has adopted (a gauge —
    # its value IS the current membership epoch); reshards counts
    # world-size transitions driven through the reshard seam; rejoins
    # counts members re-admitted at a new membership epoch; lost_workers
    # counts members evicted (heartbeat-lease expiry or launcher-observed
    # death); worker_restarts counts fleet-supervisor restarts of
    # preempted local workers; heartbeats counts liveness beats written
    # (suppressed beats under the partition_worker fault are NOT counted —
    # their absence is the observable).
    "fleet.membership_epoch", "fleet.reshards", "fleet.rejoins",
    "fleet.lost_workers", "fleet.worker_restarts", "fleet.heartbeats",
    # fleet observability plane (ISSUE 18; tpu_mx/parallel/fleet_obs.py
    # + tools/launch.py --supervise; docs/observability.md "Fleet
    # observability").  obs_records counts telemetry records this worker
    # shipped to <fleet_dir>/obs/rank-N.jsonl; the rest are the
    # CONTROLLER'S rollups: step_rate is fleet-wide steps/sec summed
    # over reporting ranks' windows; ranks_reporting counts ranks whose
    # shipped snapshot the last aggregation pass actually merged (a
    # missing rank is a reported gap, never interpolated);
    # agg_lag_seconds is the age of the OLDEST shipped snapshot the pass
    # consumed; step_skew_seconds is the max-min cross-rank wall clock
    # of the latest (epoch, step, generation)-correlated step;
    # straggler_signal is the windowed persistent-straggler detector's
    # 0/1 state and straggler_rank the rank it attributes (-1 = none) —
    # the scheduler.slo_signal/capacity_signal twin the fleet
    # supervisor surfaces in evict/degrade decisions.
    "fleet.obs_records", "fleet.step_rate", "fleet.ranks_reporting",
    "fleet.agg_lag_seconds", "fleet.step_skew_seconds",
    "fleet.straggler_signal", "fleet.straggler_rank",
    # flight recorder (tpu_mx/tracing.py; event NAMES live in its own
    # KNOWN_EVENTS catalog — blackbox_dumps counts black boxes persisted,
    # events_dropped surfaces tracing.stats()["dropped"] as a gauge
    # refreshed at flush/black-box time so silent ring overflow is
    # visible on dashboards, not only in-process)
    "tracing.blackbox_dumps", "tracing.events_dropped",
    # inference serving runtime (tpu_mx/serving/; docs/serving.md).  The
    # SLO pair: ttft = submit→first token (queueing + prefill), itl = the
    # gap between consecutive generated tokens — p50/p99 read off the
    # fixed latency buckets.  requests{state} counts every admission
    # outcome (admitted/rejected/completed/requeued); decode_steps and
    # generated_tokens are the throughput numerators; queue_depth /
    # cache_utilization are the backpressure observables.
    "serve.ttft_seconds", "serve.itl_seconds",
    "serve.tokens_per_sec", "serve.queue_depth", "serve.cache_utilization",
    "serve.requests", "serve.engine_restarts",
    "serve.decode_steps", "serve.generated_tokens",
    # decode data plane (ISSUE 9): which attention arm each call took
    # (kind=dense/paged/paged-kernel) and whether the KV block pool is
    # device-resident (1.0) or host numpy (0.0)
    "serve.decode_attention", "serve.pool_device_resident",
    # whole-step fused decode + speculative windows (ISSUE 16).
    # fused_steps counts decode steps run as ONE jitted device program
    # (serving/jax_model.py); host_crossings counts host<->device
    # boundary crossings the decode step paid (a constant 3 per fused
    # step vs 4 per LAYER host-resident) and host_crossings_per_token
    # is that step's crossings amortized over the tokens it emitted —
    # the O(1)-vs-O(layers) receipt.  spec_drafted / spec_accepted
    # count proposer-drafted tokens and the verified prefix tokens the
    # engine accepted; spec_accept_ratio is their lifetime quotient
    # (serving/speculative.py — correctness never depends on it).
    "serve.fused_steps", "serve.host_crossings",
    "serve.host_crossings_per_token",
    "serve.spec_drafted", "serve.spec_accepted",
    "serve.spec_accept_ratio",
    # SLO engine (ISSUE 11; tpu_mx/serving/slo.py + timeline.py).
    # phase_seconds{phase=...} is the per-request attribution total for
    # each typed phase (queue_wait/prefill/decode_gap/restart_penalty/
    # defer_stall/reject); the slo_* gauges are the live monitor state —
    # windowed quantile estimate, good-fraction attainment and
    # error-budget burn rate per (slo, window), and the 0/1 breach flag
    # the scheduler hook consumes.
    "serve.phase_seconds",
    "serve.slo_estimate_seconds", "serve.slo_attainment",
    "serve.slo_burn_rate", "serve.slo_breaching",
    # multi-tenant serving (ISSUE 12; tpu_mx/serving/prefix_cache.py +
    # tenancy.py).  prefill_bytes counts K/V bytes a prefill COMPUTED,
    # prefill_bytes_saved the bytes served from the shared-prefix index
    # instead (the bench receipt's ">= 2x reduction" pair);
    # prefix_hit_ratio is cached/total prompt tokens over the cache's
    # lifetime; cow_copies counts copy-on-write tail-block duplications;
    # prefix_evictions counts index entries released under pool
    # pressure.  slo_tenant_burn_rate{slo,tenant} is the per-tenant
    # worst-window burn the fairness boost consumes — tenant labels are
    # cardinality-capped (tenancy.label_for: first N tenants keep their
    # name, the rest collapse into the "_other" overflow label).
    "serve.prefix_hits", "serve.prefix_hit_ratio",
    "serve.prefill_bytes", "serve.prefill_bytes_saved",
    "serve.prefix_evictions", "serve.cow_copies",
    "serve.slo_tenant_burn_rate",
    # capacity accounting (ISSUE 14; tpu_mx/serving/accounting.py).
    # pool_bytes{tenant,kind} is the per-tenant block-pool attribution —
    # kind=amortized (1/refcount share of shared blocks; sums across
    # tenants to pool_used_bytes EXACTLY, the CI-gated identity) or
    # kind=exclusive (the full-block exclusive-if-forked cost).
    # pool_fragmentation is the free-list contiguity signal,
    # pool_high_watermark_bytes the lifetime peak, prefix_index_bytes
    # the shared-prefix index's amortized residency, pool_pinned_blocks
    # the references pinned by in-flight prefill plans.
    "serve.pool_bytes", "serve.pool_used_bytes",
    "serve.pool_fragmentation", "serve.pool_high_watermark_bytes",
    "serve.prefix_index_bytes", "serve.pool_pinned_blocks",
    # zero-regeneration recovery (ISSUE 19; tpu_mx/serving/journal.py +
    # the prefill-replay restart path).  journal_requests/tokens/bytes
    # count durable admissions, committed-token records, and bytes
    # fsync'd to the append-only journal.  replay_requests/replay_tokens
    # count restart recoveries that re-established a stream with ONE
    # prefill and the already-committed tokens that prefill replayed
    # (vs serve.decode_steps — the "zero re-decoded steps" receipt);
    # redecode_tokens counts tokens the LEGACY prompt-replay arm
    # regenerated one decode step at a time (the A/B cost the CI gate
    # compares); replay_fallbacks counts streams a torn/corrupt journal
    # loudly degraded to prompt replay.
    "serve.journal_requests", "serve.journal_tokens",
    "serve.journal_bytes",
    "serve.replay_requests", "serve.replay_tokens",
    "serve.replay_fallbacks", "serve.redecode_tokens",
    # training-side capacity twins (ISSUE 14): jit builds per batch
    # shape-signature and their wall-clock (first-call XLA compile
    # included), the newest checkpoint's manifest bytes-on-disk, and
    # the process's host resident set (refreshed at every flush /
    # black-box export, like tracing.events_dropped)
    "train_step.compiles", "train_step.compile_seconds",
    "checkpoint.bytes_on_disk", "host.rss_bytes",
    # module-API training (tpu_mx/callback.py)
    "speedometer.samples_per_sec",
})

_lock = threading.RLock()
_metrics: dict = {}          # (name, labels_tuple) -> metric object

# fleet identity (ISSUE 18): once the fleet runtime adopts a membership
# epoch (tpu_mx/parallel/fleet.py::_adopt), every exported record is
# stamped with this process's rank and the membership generation the
# snapshot reflects — the cross-worker aggregator
# (tpu_mx/parallel/fleet_obs.py) keys stale-record exclusion on the
# stamp.  Both None (the static-world default) means no stamping at all:
# records from non-fleet processes are byte-identical to pre-fleet ones.
_fleet_identity = {"rank": None, "generation": None}
_UNSET = object()


def set_fleet_identity(rank=_UNSET, generation=_UNSET):
    """Stamp every subsequently exported record with this process's
    fleet identity.  Omitted fields keep their value; passing None
    clears one.  The fleet runtime calls this on epoch adoption —
    instrumented code never needs to."""
    with _lock:
        if rank is not _UNSET:
            _fleet_identity["rank"] = None if rank is None else int(rank)
        if generation is not _UNSET:
            _fleet_identity["generation"] = \
                None if generation is None else int(generation)


def fleet_identity():
    """The live ``(rank, generation)`` stamp, or ``(None, None)``."""
    with _lock:
        return _fleet_identity["rank"], _fleet_identity["generation"]

# the window clock.  Monotonic (a wall-clock step must not expire or
# resurrect subwindows); module-level so tests can substitute a fake
# clock and drive subwindow rollover deterministically.
_monotonic = time.monotonic


def _labels_key(labels):
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _WindowRing:
    """Ring of ``n`` subwindow slots covering the trailing ``seconds``.

    Each slot is stamped with the epoch (``monotonic // slot_seconds``)
    it belongs to; writing into a slot whose stamp is stale resets it
    first, and reads simply skip slots whose epoch has rotated out — so
    neither writes nor reads ever pay more than O(n) and memory is
    bounded no matter how long the process runs.  All methods are called
    under the registry lock."""

    __slots__ = ("seconds", "n", "slot_seconds", "epochs", "slots",
                 "created", "_make_slot")

    def __init__(self, seconds, n, make_slot):
        seconds = float(seconds)
        n = int(n)
        if seconds <= 0 or n < 2:
            raise ValueError("window needs seconds > 0 and >= 2 subwindows")
        self.seconds = seconds
        self.n = n
        self.slot_seconds = seconds / n
        self.epochs = [-1] * n
        self.slots = [make_slot() for _ in range(n)]
        self.created = _monotonic()
        self._make_slot = make_slot

    def slot(self):
        """The live slot for the current epoch (reset if stale)."""
        e = int(_monotonic() // self.slot_seconds)
        i = e % self.n
        if self.epochs[i] != e:
            self.epochs[i] = e
            self.slots[i] = self._make_slot()
        return self.slots[i]

    def live(self, window=None):
        """(covered_seconds, [slot, ...]) for the trailing ``window``
        (clamped to the ring horizon; quantized to whole subwindows).
        ``covered`` is additionally clamped to the ring's AGE (floored
        at one subwindow): a 5 s-old ring must not claim 60 s of
        coverage, or every rate derived from it under-reports ~12x
        during exactly the warm-up an operator watches."""
        if window is None:
            horizon = self.seconds
        else:
            horizon = min(max(float(window), self.slot_seconds),
                          self.seconds)
        k = max(1, min(self.n, int(math.ceil(horizon / self.slot_seconds
                                             - 1e-9))))
        now = _monotonic()
        e = int(now // self.slot_seconds)
        out = [self.slots[i] for i in range(self.n)
               if self.epochs[i] >= 0 and e - self.epochs[i] < k]
        covered = min(k * self.slot_seconds,
                      max(now - self.created, self.slot_seconds))
        return covered, out


class _Metric:
    __slots__ = ("name", "labels")
    kind = None

    def __init__(self, name, labels):
        self.name = name
        self.labels = labels


class Counter(_Metric):
    """Monotonically increasing count (resets only with the process).
    Additionally keeps a subwindow ring so :meth:`window_delta` /
    :meth:`window_rate` answer "how many in the last N seconds" without
    a scraper diffing snapshots."""

    __slots__ = ("value", "_win")
    kind = "counter"

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self.value = 0
        self._win = _WindowRing(WINDOW_SECONDS, WINDOW_SUBWINDOWS,
                                lambda: [0])

    def inc(self, n=1):
        with _lock:
            self.value += n
            self._win.slot()[0] += n
        return self

    def configure_window(self, seconds, subwindows=None):
        """Resize the subwindow ring (resets the WINDOW contents only;
        the cumulative value is untouched)."""
        with _lock:
            self._win = _WindowRing(seconds,
                                    subwindows or WINDOW_SUBWINDOWS,
                                    lambda: [0])
        return self

    def window_delta(self, window=None):
        """Increments observed over the trailing ``window`` seconds
        (default: the full ring horizon, quantized to subwindows)."""
        with _lock:
            _, slots = self._win.live(window)
            return sum(s[0] for s in slots)

    def window_rate(self, window=None):
        """Increments per second over the trailing window."""
        with _lock:
            covered, slots = self._win.live(window)
            return sum(s[0] for s in slots) / covered

    def _record(self, ts):
        rec = _rec(self, ts, self.value)
        with _lock:
            covered, slots = self._win.live()
            rec["window"] = {"seconds": covered,
                             "value": sum(s[0] for s in slots)}
        return rec


class Gauge(_Metric):
    """Last-written value (e.g. examples/sec)."""

    __slots__ = ("value",)
    kind = "gauge"

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self.value = 0.0

    def set(self, value):
        with _lock:
            self.value = float(value)
        return self

    def _record(self, ts):
        return _rec(self, ts, self.value)


class Histogram(_Metric):
    """Fixed-bucket distribution; default buckets are the log-scale
    latency ladder (:data:`LATENCY_BUCKETS`, seconds).  Tracks count, sum,
    min and max alongside the cumulative bucket counts.  ``unit`` rides
    the JSONL record so renderers know whether ms-scaling applies.

    Every histogram additionally maintains a **sliding window**: a ring
    of subwindow slots (each a full bucket array + count/sum/min/max)
    covering the trailing :data:`WINDOW_SECONDS`.  Merging the live
    slots answers "p99 over the last N seconds" in O(subwindows ×
    buckets) with bounded memory — the live-SLO read the serving
    monitor (tpu_mx/serving/slo.py) sits on."""

    __slots__ = ("buckets", "counts", "count", "sum", "min", "max", "unit",
                 "dropped_nonfinite", "_win")
    kind = "histogram"

    def __init__(self, name, labels, buckets=None, unit="seconds"):
        super().__init__(name, labels)
        self.unit = unit
        # sorted + deduped so cumulative()/exposition() emit `le` bounds
        # in ascending order with +Inf last, per the Prometheus text
        # format, whatever order a caller passed
        self.buckets = tuple(sorted({float(b)
                                     for b in (buckets or LATENCY_BUCKETS)}))
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf overflow
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self.dropped_nonfinite = 0   # NaN/±Inf observations, never bucketed
        self._win = _WindowRing(WINDOW_SECONDS, WINDOW_SUBWINDOWS,
                                self._make_slot)

    def _make_slot(self):
        # [bucket counts, count, sum, min, max] — one subwindow's state
        return [[0] * (len(self.buckets) + 1), 0, 0.0, None, None]

    def observe(self, value):
        value = float(value)
        if not math.isfinite(value):
            # a non-finite sample has no honest bucket: bisect would
            # file NaN under the FASTEST bucket (every `edge < nan`
            # compare is False), the overflow slot would force false
            # breaches for legitimate >30s samples, and one nan+x
            # would poison the running sum forever — breaking the
            # strict-JSON JSONL/black-box contract.  Drop it VISIBLY:
            # the dropped_nonfinite field rides every record.
            with _lock:
                self.dropped_nonfinite += 1
            return self
        with _lock:
            # first bucket whose upper bound >= value (values above the
            # last edge land in the +Inf overflow slot)
            i = bisect_left(self.buckets, value)
            self.counts[i] += 1
            self.count += 1
            self.sum += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
            s = self._win.slot()
            s[0][i] += 1
            s[1] += 1
            s[2] += value
            s[3] = value if s[3] is None else min(s[3], value)
            s[4] = value if s[4] is None else max(s[4], value)
        return self

    def configure_window(self, seconds, subwindows=None):
        """Resize the subwindow ring (resets the WINDOW contents only;
        cumulative bucket state is untouched).  The bench serve leg uses
        this to give the SLO pair a horizon covering a whole arm."""
        with _lock:
            self._win = _WindowRing(seconds,
                                    subwindows or WINDOW_SUBWINDOWS,
                                    self._make_slot)
        return self

    def cumulative(self):
        """[(upper_bound | "+Inf", cumulative_count), ...] — monotone."""
        with _lock:
            cum = _cumulate(self.counts)
        out = list(zip(self.buckets, cum))
        out.append(("+Inf", cum[-1]))
        return out

    # -- windowed reads ------------------------------------------------------
    def _window_merged(self, window=None):
        """(covered_seconds, counts, count, sum, min, max) — the live
        subwindows merged; called under the registry lock."""
        covered, slots = self._win.live(window)
        counts = [0] * (len(self.buckets) + 1)
        n, total, mn, mx = 0, 0.0, None, None
        for s in slots:
            for j, c in enumerate(s[0]):
                counts[j] += c
            n += s[1]
            total += s[2]
            if s[3] is not None:
                mn = s[3] if mn is None else min(mn, s[3])
                mx = s[4] if mx is None else max(mx, s[4])
        return covered, counts, n, total, mn, mx

    def window_cumulative(self, window=None):
        """Like :meth:`cumulative`, over the trailing window only."""
        with _lock:
            _, counts, _, _, _, _ = self._window_merged(window)
        cum = _cumulate(counts)
        out = list(zip(self.buckets, cum))
        out.append(("+Inf", cum[-1]))
        return out

    def window_stats(self, window=None):
        """{seconds, count, sum, min, max} over the trailing window."""
        with _lock:
            covered, _, n, total, mn, mx = self._window_merged(window)
        return {"seconds": covered, "count": n, "sum": total,
                "min": mn, "max": mx}

    def window_quantile(self, q, window=None):
        """Bucket-merge estimate of the ``q`` quantile over the trailing
        window (within-bucket linear interpolation, clamped to the
        window's observed min/max), or None when the window is empty.
        O(subwindows × buckets)."""
        with _lock:
            _, counts, n, _, mn, mx = self._window_merged(window)
        if not n:
            return None
        return _quantile(self.buckets, _cumulate(counts), q,
                         vmin=mn, vmax=mx)

    def window_fraction_le(self, threshold, window=None):
        """Fraction of window samples <= ``threshold`` seconds (linear
        interpolation inside the straddling bucket; overflow-bucket
        samples count as above any finite threshold — conservative for
        SLO attainment), or None when the window is empty."""
        with _lock:
            _, counts, n, _, mn, mx = self._window_merged(window)
        if not n:
            return None
        return _fraction_le(self.buckets, _cumulate(counts),
                            float(threshold), vmin=mn, vmax=mx)

    def quantile(self, q):
        """Lifetime (cumulative-since-start) quantile estimate, same
        bucket interpolation as :meth:`window_quantile`."""
        with _lock:
            counts = list(self.counts)
            n, mn, mx = self.count, self.min, self.max
        if not n:
            return None
        return _quantile(self.buckets, _cumulate(counts), q,
                         vmin=mn, vmax=mx)

    def _record(self, ts):
        rec = _rec(self, ts, self.count)
        rec["sum"] = self.sum
        rec["unit"] = self.unit
        if self.count:
            rec["min"] = self.min
            rec["max"] = self.max
        if self.dropped_nonfinite:
            rec["dropped_nonfinite"] = self.dropped_nonfinite
        rec["buckets"] = [[b, c] for b, c in self.cumulative()]
        with _lock:
            covered, counts, n, total, mn, mx = self._window_merged()
        win = {"seconds": covered, "count": n, "sum": total}
        if n:
            win["min"] = mn
            win["max"] = mx
        cum = _cumulate(counts)
        win["buckets"] = ([[b, c] for b, c in zip(self.buckets, cum)]
                          + [["+Inf", cum[-1]]])
        rec["window"] = win
        return rec


def _rec(metric, ts, value):
    rec = {"name": metric.name, "type": metric.kind, "value": value,
           "ts": ts}
    if metric.labels:
        rec["labels"] = dict(metric.labels)
    if _fleet_identity["rank"] is not None:
        rec["rank"] = _fleet_identity["rank"]
    if _fleet_identity["generation"] is not None:
        rec["fleet_generation"] = _fleet_identity["generation"]
    return rec


# ---------------------------------------------------------------------------
# bucket quantile math (shared by the live monitor and tools/slo_report.py,
# which loads this module standalone — keep these stdlib-pure)
# ---------------------------------------------------------------------------
def _cumulate(counts):
    """Per-bucket counts (overflow last) → cumulative counts, the +Inf
    overflow included as the last entry — the shape every quantile /
    fraction / record path consumes."""
    cum, acc = [], 0
    for c in counts[:-1]:
        acc += c
        cum.append(acc)
    cum.append(acc + counts[-1])
    return cum


def _quantile(bounds, cum, q, vmin=None, vmax=None):
    """Estimate the ``q`` quantile from cumulative bucket counts.

    ``bounds`` are the ascending finite upper edges; ``cum`` the
    cumulative counts per bucket INCLUDING the +Inf overflow as its last
    entry.  Linear interpolation inside the straddling bucket; the
    estimate is clamped to [vmin, vmax] when known (which makes the
    all-samples-in-one-bucket case exact when min == max).  Returns None
    on an empty distribution."""
    total = cum[-1]
    if total <= 0:
        return None
    q = min(max(float(q), 0.0), 1.0)
    rank = q * total
    prev_c, prev_b = 0, 0.0
    est = None
    for b, c in zip(bounds, cum):
        if c >= rank and c > prev_c:
            frac = (rank - prev_c) / (c - prev_c)
            est = prev_b + (b - prev_b) * max(0.0, min(1.0, frac))
            break
        prev_c, prev_b = c, b
    if est is None:
        # the rank lives in the +Inf overflow bucket: the best bounded
        # answer is the observed max (or the last finite edge)
        est = vmax if vmax is not None else (bounds[-1] if bounds else 0.0)
    if vmin is not None:
        est = max(est, vmin)
    if vmax is not None:
        est = min(est, vmax)
    return est


def _fraction_le(bounds, cum, threshold, vmin=None, vmax=None):
    """Fraction of samples <= ``threshold`` from cumulative bucket
    counts (``cum`` includes the +Inf overflow last).  Interpolates
    inside the straddling bucket; overflow samples count as ABOVE any
    threshold below the observed max (conservative for SLO attainment).
    Known ``vmin``/``vmax`` short-circuit the degenerate cases exactly:
    a threshold at or above every observed sample is full attainment
    (sound because observe() drops non-finite values — every counted
    sample, overflow included, is <= vmax), one below every sample is
    zero."""
    total = cum[-1]
    if total <= 0:
        return None
    if vmax is not None and threshold >= vmax:
        return 1.0
    if vmin is not None and threshold < vmin:
        return 0.0
    prev_c, prev_b = 0, 0.0
    for b, c in zip(bounds, cum):
        if threshold <= b:
            if threshold >= b:
                return c / total
            width = b - prev_b
            frac = (threshold - prev_b) / width if width > 0 else 1.0
            return (prev_c + (c - prev_c) * max(0.0, min(1.0, frac))) / total
        prev_c, prev_b = c, b
    return (cum[-2] if len(cum) > 1 else cum[-1]) / total


def _split_record_buckets(buckets):
    """A record-shaped ``[[bound | "+Inf", cum], ...]`` list split into
    (finite_bounds, cum_counts_incl_overflow)."""
    bounds = [float(b) for b, _ in buckets if b != "+Inf"]
    cum = [c for b, c in buckets if b != "+Inf"]
    inf = [c for b, c in buckets if b == "+Inf"]
    cum.append(inf[0] if inf else (cum[-1] if cum else 0))
    return bounds, cum


def quantile_from_cumulative(buckets, q, vmin=None, vmax=None):
    """The ``q`` quantile estimate from a record-shaped cumulative
    bucket list (``[[bound | "+Inf", count], ...]`` — the JSONL/window
    schema), or None when empty.  tools/slo_report.py reads live-window
    SLO state from snapshots with exactly this call."""
    bounds, cum = _split_record_buckets(buckets)
    return _quantile(bounds, cum, q, vmin=vmin, vmax=vmax)


def fraction_le_from_cumulative(buckets, threshold, vmin=None, vmax=None):
    """Fraction of samples <= ``threshold`` from a record-shaped
    cumulative bucket list, or None when empty (``vmin``/``vmax`` —
    e.g. a window record's min/max — make the all-above/all-below
    cases exact)."""
    bounds, cum = _split_record_buckets(buckets)
    return _fraction_le(bounds, cum, float(threshold),
                        vmin=vmin, vmax=vmax)


# ---------------------------------------------------------------------------
# SLO target specs ("itl_p99 < 50ms") — parsed here so the serving
# monitor and the jax-less report tool share one grammar
# ---------------------------------------------------------------------------
# the serving pair, shared by serving.SLOMonitor's default arming and
# tools/slo_report.py's default evaluation — one source, no drift
DEFAULT_SLOS = ("ttft_p99 < 500ms", "itl_p99 < 50ms")

# the attribution invariant's bar: |sum(phases) - latency| must stay
# within this fraction of the latency (plus a 1 ms absolute floor for
# sub-ms requests).  Asserted in-process by the serve CI tier and
# re-checked offline by tools/slo_report.py --validate — shared here so
# the two checks can never drift apart.
ATTRIBUTION_TOLERANCE = 0.05

SLO_METRIC_ALIASES = {
    "itl": "serve.itl_seconds",
    "ttft": "serve.ttft_seconds",
}

_SLO_SPEC_RE = re.compile(
    r"^\s*([A-Za-z0-9_.]+?)_p(\d{1,2}(?:\.\d+)?)\s*<\s*"
    r"([0-9]*\.?[0-9]+)\s*(us|ms|s)\s*$")

_SLO_UNITS = {"us": 1e-6, "ms": 1e-3, "s": 1.0}


def parse_slo_spec(spec):
    """``"itl_p99 < 50ms"`` → ``{name, metric, quantile,
    threshold_seconds, objective}``.  The left side is a metric alias
    (``itl``/``ttft``) or a full histogram name, suffixed ``_p<NN>``;
    the right side a latency with unit ``us``/``ms``/``s``.  The
    objective (required good fraction) defaults to the quantile: "p99
    below X" means 99% of samples must land below X, i.e. an error
    budget of 1%."""
    m = _SLO_SPEC_RE.match(str(spec))
    if not m:
        raise ValueError(
            f"unparseable SLO spec {spec!r} (want e.g. 'itl_p99 < 50ms')")
    base, pct, value, unit = m.groups()
    quantile = float(pct) / 100.0
    if not 0.0 < quantile < 1.0:
        raise ValueError(f"SLO spec {spec!r}: p{pct} out of (0, 100)")
    return {
        "name": f"{base}_p{pct}",
        "metric": SLO_METRIC_ALIASES.get(base, base),
        "quantile": quantile,
        "threshold_seconds": float(value) * _SLO_UNITS[unit],
        "objective": quantile,
    }


def _get_or_make(cls, name, labels, **kw):
    key = (name, _labels_key(labels))
    with _lock:
        m = _metrics.get(key)
        if m is None:
            m = _metrics[key] = cls(name, _labels_key(labels), **kw)
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}, "
                f"not {cls.kind}")
        return m


def counter(name, **labels):
    """Create-or-fetch the Counter `name` (labels make distinct series)."""
    return _get_or_make(Counter, name, labels)


def gauge(name, **labels):
    """Create-or-fetch the Gauge `name`."""
    return _get_or_make(Gauge, name, labels)


def histogram(name, buckets=None, unit="seconds", **labels):
    """Create-or-fetch the Histogram `name`; `buckets` and `unit` only
    apply on first creation (fixed thereafter — merged snapshots depend
    on the bucket edges).  Names in ``_DEFAULT_BUCKETS`` (the serving
    SLO pair and phase attribution) default to the dense
    :data:`SLO_LATENCY_BUCKETS` ladder so every creation site agrees
    without repeating the edges."""
    if buckets is None:
        buckets = _DEFAULT_BUCKETS.get(name)
    return _get_or_make(Histogram, name, labels, buckets=buckets, unit=unit)


def get(name, **labels):
    """The already-registered metric, or None (no create side effect)."""
    with _lock:
        return _metrics.get((name, _labels_key(labels)))


def series(name):
    """Every registered series of ``name`` as ``[(labels_dict, metric),
    ...]`` (no create side effect).  The per-tenant SLO evaluation uses
    this to find the tenant-labeled variants of a target's histogram
    without knowing the tenant set in advance."""
    with _lock:
        return [(dict(m.labels), m)
                for (n, _), m in _metrics.items() if n == name]


def reset():
    """Drop every metric and the fleet-identity stamp (test hook)."""
    with _lock:
        _metrics.clear()
        _fleet_identity.update(rank=None, generation=None)
    _finalized.clear()


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
class span:
    """Context manager: time a region into the histogram `name` and merge
    the interval into ``mx.profiler``'s chrome-trace stream when the
    profiler is recording (one Perfetto timeline for spans + XLA)."""

    __slots__ = ("name", "labels", "_t0")

    def __init__(self, name, **labels):
        self.name = name
        self.labels = labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        histogram(self.name, **self.labels).observe(t1 - self._t0)
        try:
            from . import profiler
            profiler.record_span(self.name, self._t0, t1)
        except Exception:
            pass  # standalone load (no package) or profiler torn down
        return False


# ---------------------------------------------------------------------------
# JSONL exporter
# ---------------------------------------------------------------------------
def configured_path():
    """The JSONL sink from the TPUMX_TELEMETRY env var, or None."""
    return os.environ.get("TPUMX_TELEMETRY") or None


def snapshot():
    """One record per live metric, sharing a wall-clock ``ts``.

    Built entirely under the registry lock (no I/O happens here): a
    concurrent ``observe()`` between reading ``count`` and the bucket
    array would otherwise produce a record violating the schema's own
    +Inf-count == value invariant."""
    ts = time.time()
    with _lock:
        return [m._record(ts) for m in _metrics.values()]


def flush(path=None, final=False):
    """Append one snapshot to the JSONL sink (`path` or TPUMX_TELEMETRY).

    No sink configured → no-op (returns None), which is what makes
    instrumentation free to call this unconditionally.  ``final=True``
    rewrites the file — full history + this snapshot — through
    ``checkpoint.atomic_write``, so the at-exit dump can never leave a
    truncated file; intermediate flushes are plain appends (cheap, and a
    torn tail there is recoverable line-by-line).  Returns the records."""
    path = path or configured_path()
    if not path:
        return None
    _refresh_bridge_gauges()
    recs = snapshot()
    payload = "".join(json.dumps(r, sort_keys=True) + "\n" for r in recs)
    # The registry _lock is NEVER held across file I/O: the write path
    # below re-enters instrumented code (atomic_write counts itself;
    # chaos faults count their own firing), and holding _lock here would
    # invert against the locks those layers hold (cfg.lock -> _lock vs
    # _lock -> cfg.lock).  _flush_io_lock serializes concurrent flush()
    # calls instead, so a final read-modify-rewrite cannot drop a
    # concurrent append.  Earlier snapshots are re-read from disk for the
    # final rewrite — no in-memory history accumulates over a long run.
    with _flush_io_lock:
        if final:
            _finalized.add(path)
            try:
                with open(path, encoding="utf-8") as f:
                    prev = f.read()
            except OSError:
                prev = ""
            try:
                from .checkpoint import atomic_write
                with atomic_write(path, "w") as f:
                    f.write(prev + payload)
            except ImportError:  # standalone module load: plain rewrite
                # tpumx-lint: disable=durability -- degraded mode only:
                # this module is loadable WITHOUT the package (no
                # checkpoint layer to import); a torn JSONL tail is
                # recoverable line-by-line
                with open(path, "w", encoding="utf-8") as f:
                    f.write(prev + payload)
        else:
            with open(path, "a", encoding="utf-8") as f:
                f.write(payload)
    return recs


def _host_rss_bytes():
    """The process's resident set in bytes (linux /proc fast path;
    getrusage peak-RSS fallback elsewhere), or None when unreadable —
    the host-memory capacity twin (ISSUE 14): a serving pool ledger is
    half the story if the host process itself is the thing growing."""
    try:
        with open("/proc/self/statm", encoding="ascii") as f:
            resident_pages = int(f.read().split()[1])
        return resident_pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        peak = int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
        # peak, not live — and the unit is platform-defined: linux/BSD
        # report KiB, darwin reports BYTES (a blanket ×1024 would
        # inflate a mac's gauge three orders of magnitude)
        return peak if sys.platform == "darwin" else peak * 1024
    except Exception:
        return None


def _refresh_bridge_gauges():
    """Pull cross-module observables into the registry right before a
    snapshot leaves the process: tracing.stats()["dropped"] becomes the
    ``tracing.events_dropped`` gauge (silent ring overflow visible in
    every exported snapshot and black box, not only in-process) and the
    host resident set becomes ``host.rss_bytes``.  Only reads a tracing
    module that is ALREADY imported (never imports — this module stays
    standalone-loadable), and tracing's lock is released before the
    gauge write (no nested lock order)."""
    rss = _host_rss_bytes()
    if rss is not None:
        gauge("host.rss_bytes").set(float(rss))
    if not __package__:
        return  # standalone module load: no package, no other bridges
    mod = sys.modules.get(__package__ + ".tracing")
    if mod is None:
        return
    try:
        dropped = mod.stats()["dropped"]
        gauge("tracing.events_dropped").set(float(dropped))
    except Exception:
        pass  # a torn-down tracing module must not break a flush


# paths a final flush already rewrote — the atexit hook must not append a
# duplicate final snapshot after an explicit flush(final=True)
_finalized: set = set()
_flush_io_lock = threading.Lock()


@atexit.register
def _flush_at_exit():  # pragma: no cover — exercised via subprocess (ci obs)
    try:
        path = configured_path()
        if path and _metrics and path not in _finalized:
            flush(final=True)
    except Exception:
        pass


def validate_record(rec):
    """Raise ValueError unless `rec` is a schema-valid telemetry record.

    Schema (the contract tools/ci.py's `obs` tier enforces): every record
    has a str ``name``, ``type`` in {counter, gauge, histogram}, numeric
    ``value`` and ``ts``; histograms additionally carry a numeric ``sum``
    and cumulative ``buckets`` [[bound, count], ...] whose counts are
    monotone non-decreasing, whose last bound is "+Inf", and whose total
    equals ``value``."""
    if not isinstance(rec, dict):
        raise ValueError(f"record is {type(rec).__name__}, not an object")
    name = rec.get("name")
    if not isinstance(name, str) or not name:
        raise ValueError(f"record missing name: {rec!r}")
    kind = rec.get("type")
    if kind not in ("counter", "gauge", "histogram"):
        raise ValueError(f"{name}: bad type {kind!r}")
    for field in ("value", "ts"):
        if not isinstance(rec.get(field), (int, float)) \
                or isinstance(rec.get(field), bool):
            raise ValueError(f"{name}: missing numeric {field!r}")
    if "labels" in rec and not (
            isinstance(rec["labels"], dict)
            and all(isinstance(k, str) and isinstance(v, str)
                    for k, v in rec["labels"].items())):
        raise ValueError(f"{name}: labels must be a str->str object")
    # the fleet-identity stamp (ISSUE 18) is optional — records from
    # static-world processes simply lack both keys and stay valid
    for field in ("rank", "fleet_generation"):
        v = rec.get(field)
        if v is not None and (not isinstance(v, int)
                              or isinstance(v, bool)):
            raise ValueError(f"{name}: {field!r} must be int, got {v!r}")
    if kind == "histogram":
        if not isinstance(rec.get("sum"), (int, float)):
            raise ValueError(f"{name}: histogram missing numeric 'sum'")
        buckets = rec.get("buckets")
        if not isinstance(buckets, list) or not buckets:
            raise ValueError(f"{name}: histogram missing 'buckets'")
        prev = None
        for entry in buckets:
            if (not isinstance(entry, list) or len(entry) != 2
                    or not isinstance(entry[1], int)):
                raise ValueError(f"{name}: malformed bucket {entry!r}")
            if prev is not None and entry[1] < prev:
                raise ValueError(
                    f"{name}: bucket counts not monotone "
                    f"({entry[1]} after {prev})")
            prev = entry[1]
        if buckets[-1][0] != "+Inf":
            raise ValueError(f"{name}: last bucket bound must be '+Inf', "
                             f"got {buckets[-1][0]!r}")
        if buckets[-1][1] != rec["value"]:
            raise ValueError(
                f"{name}: +Inf bucket count {buckets[-1][1]} != "
                f"value {rec['value']}")
    if "window" in rec:
        _validate_window(name, kind, rec["window"])
    return rec


def _validate_window(name, kind, win):
    """The optional ``window`` sub-object (trailing-window state riding
    counter/histogram records): numeric ``seconds``; counters carry a
    numeric ``value``, histograms a numeric ``count``/``sum`` and a
    monotone cumulative bucket list ending at ``+Inf`` whose total
    equals the window count — the same invariants as the record
    proper.  Records written before the window layer simply lack the
    key and stay valid."""
    if not isinstance(win, dict):
        raise ValueError(f"{name}: 'window' must be an object")
    if not isinstance(win.get("seconds"), (int, float)) \
            or isinstance(win.get("seconds"), bool):
        raise ValueError(f"{name}: window missing numeric 'seconds'")
    if kind == "counter":
        if not isinstance(win.get("value"), (int, float)) \
                or isinstance(win.get("value"), bool):
            raise ValueError(f"{name}: counter window missing 'value'")
        return
    if kind != "histogram":
        raise ValueError(f"{name}: {kind} records carry no window")
    for field in ("count", "sum"):
        if not isinstance(win.get(field), (int, float)) \
                or isinstance(win.get(field), bool):
            raise ValueError(f"{name}: window missing numeric {field!r}")
    buckets = win.get("buckets")
    if not isinstance(buckets, list) or not buckets:
        raise ValueError(f"{name}: window missing 'buckets'")
    prev = None
    for entry in buckets:
        if (not isinstance(entry, list) or len(entry) != 2
                or not isinstance(entry[1], int)):
            raise ValueError(f"{name}: malformed window bucket {entry!r}")
        if prev is not None and entry[1] < prev:
            raise ValueError(f"{name}: window bucket counts not monotone")
        prev = entry[1]
    if buckets[-1][0] != "+Inf":
        raise ValueError(f"{name}: window's last bucket must be '+Inf'")
    if buckets[-1][1] != win["count"]:
        raise ValueError(
            f"{name}: window +Inf count {buckets[-1][1]} != "
            f"count {win['count']}")


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name):
    return "tpumx_" + _NAME_RE.sub("_", name)


def _prom_escape(v):
    """Label-value escaping per the Prometheus text format: backslash,
    double-quote and line-feed — in that order (escaping the escape
    character first keeps the round trip unambiguous)."""
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _prom_labels(pairs):
    if not pairs:
        return ""
    body = ",".join('%s="%s"' % (_NAME_RE.sub("_", k), _prom_escape(v))
                    for k, v in pairs)
    return "{" + body + "}"


def _prom_num(v):
    return repr(float(v)) if isinstance(v, float) else str(v)


def exposition():
    """The registry in Prometheus text exposition format (one string —
    serve it over whatever transport exists; no HTTP server here).
    Counters get the conventional ``_total`` suffix; histograms emit the
    ``_bucket{le=...}`` / ``_sum`` / ``_count`` family.  Rendered under
    the registry lock (pure string building, no I/O) so a concurrent
    ``observe()`` cannot tear a histogram's bucket/sum/count family."""
    with _lock:
        return _exposition_locked()


def _exposition_locked():
    metrics = sorted(_metrics.values(), key=lambda m: (m.name, m.labels))
    lines = []
    typed = set()

    def type_line(pname, kind):
        if pname not in typed:
            typed.add(pname)
            lines.append(f"# TYPE {pname} {kind}")

    for m in metrics:
        if m.kind == "counter":
            pname = _prom_name(m.name) + "_total"
            type_line(pname, "counter")
            lines.append(f"{pname}{_prom_labels(m.labels)} "
                         f"{_prom_num(m.value)}")
        elif m.kind == "gauge":
            pname = _prom_name(m.name)
            type_line(pname, "gauge")
            lines.append(f"{pname}{_prom_labels(m.labels)} "
                         f"{_prom_num(m.value)}")
        else:
            pname = _prom_name(m.name)
            type_line(pname, "histogram")
            for bound, cum in m.cumulative():
                le = "+Inf" if bound == "+Inf" else repr(float(bound))
                lab = _prom_labels(tuple(m.labels) + (("le", le),))
                lines.append(f"{pname}_bucket{lab} {cum}")
            lines.append(f"{pname}_sum{_prom_labels(m.labels)} "
                         f"{_prom_num(m.sum)}")
            lines.append(f"{pname}_count{_prom_labels(m.labels)} "
                         f"{m.count}")
    return "\n".join(lines) + ("\n" if lines else "")
