"""mx.name — NameManager / Prefix (REF:python/mxnet/name.py).

Symbol auto-names (`fullyconnected0`, ...) route through the active
NameManager; `with mx.name.Prefix("block1_"):` prefixes every auto name
created in the scope, exactly the reference's mechanism behind
`Block.name_scope()`'s symbolic twin."""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]

_tls = threading.local()


def _current():
    return getattr(_tls, "manager", None)


class NameManager:
    """Counts per-hint and yields `hint0, hint1, ...`; subclass `get` for
    custom schemes."""

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name is not None:
            return name
        i = self._counter.get(hint, 0)
        self._counter[hint] = i + 1
        return f"{hint}{i}"

    def __enter__(self):
        self._old = _current()
        _tls.manager = self
        return self

    def __exit__(self, *exc):
        _tls.manager = self._old
        return False


class Prefix(NameManager):
    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)
