"""Self-healing training: the supervisor closes the crash→resume loop.

The stack can *survive* a crash (durable manifests + elastic resume,
docs/robustness.md) and *see* a failure (telemetry,
docs/observability.md), but until this module nothing closed the loop at
runtime: a hung collective, a NaN loss, or a transient filesystem fault
still killed the whole job and waited for a human to re-launch.  The
supervisor composes checkpointing, elasticity, chaos, and telemetry into
one control loop — the difference between "crash-safe" and "self-healing":

1. **Hung-step watchdog** — :func:`run_with_deadline` runs the step on a
   daemon thread and joins with a timeout (`elastic.barrier`'s pattern,
   generalized): a stalled collective or compile becomes a catchable
   :class:`WatchdogTimeout` (a ``WorkerFailure``) instead of an eternal
   hang.  The deadline is *recompile-aware*: when a jit (re)build started
   during the step (``grace_signal`` — by default the global
   ``train_step.recompiles`` counter — moved), the watchdog grants one
   ``grace`` extension instead of killing a legitimate compile.
2. **Numeric sentinel** — :class:`NumericSentinel` watches every observed
   loss (and optional grad norm) for NaN/Inf and spikes.  The first
   ``skip_limit`` consecutive bad batches are *skipped* (flagged, counted,
   excluded from the spike baseline — a single bad batch often
   self-heals); one more raises :class:`NumericDivergence`, which rolls
   training back to the last **verified** checkpoint (the poisoned epoch
   was never saved — divergence aborts the epoch before its save) and
   re-enters after a cooldown.
3. **Classified retry** — :func:`classify` sorts failures: *transient*
   (``OSError``, ``WorkerFailure``, ``chaos.ChaosCrash``) get bounded,
   jittered-backoff in-process restarts resuming from the manifest;
   *numeric* (:class:`NumericDivergence`) gets rollback + cooldown;
   everything else is *fatal* (a programming error) and propagates
   immediately — retrying a ``TypeError`` hides bugs.
4. **Graceful degradation** — when ``max_restarts`` / ``max_rollbacks``
   is exhausted the supervisor makes one clean durable final save, sets
   the ``supervisor.degraded`` gauge, invokes the ``on_degraded`` hook,
   and returns a structured :class:`SupervisorResult` instead of dying
   mid-flight.

Every recovery path is *provoked* in tests, not assumed:
``contrib.chaos``'s ``nan_after`` / ``hang_step`` knobs inject divergence
and hangs deterministically (tests/test_supervisor.py), and ``tools/ci.py``'s
``soak`` tier runs a whole training job under a fixed-seed randomized
fault schedule (crash, torn write, hang, NaN) that must end with a
verified checkpoint and a finite loss.

Usage — a Gluon/CompiledTrainStep loop::

    sup = supervisor.Supervisor(
        save_fn=lambda e: elastic.save_checkpoint(prefix, e, net=net),
        restore_fn=lambda: elastic.auto_resume(prefix, net=net),
        deadline=60.0)
    def epoch_fn(epoch):
        for batch in batches():
            sup.step(lambda: train_step.step(*batch))   # returns the loss
    result = sup.run(epoch_fn, begin_epoch=0, num_epoch=90)

or the Module API: ``module.fit(..., supervised=supervisor.Supervise(
prefix="ck"))`` wires save/rollback to ``module.save_checkpoint`` /
``elastic.auto_resume`` automatically.
"""
from __future__ import annotations

import logging
import math
import os
import random
import threading
import time
from collections import deque

from .base import MXNetError
from . import checkpoint as _ckpt
from . import telemetry as _telemetry
from . import tracing as _tracing
from .contrib.chaos import ChaosCrash
from .elastic import WorkerFailure

__all__ = ["Supervisor", "Supervise", "SupervisorResult", "NumericSentinel",
           "NumericDivergence", "DataCorruption", "WatchdogTimeout",
           "run_with_deadline", "classify", "for_module",
           "TRANSIENT_EXCEPTIONS"]

log = logging.getLogger(__name__)


class NumericDivergence(MXNetError):
    """The numeric sentinel gave up on skipping: training has diverged
    (consecutive NaN/Inf losses or spikes past the skip budget) and must
    roll back to the last verified checkpoint."""


class DataCorruption(MXNetError):
    """Silent data corruption, caught loudly (parallel/integrity.py): a
    cross-replica fingerprint vote disagreed, a shadow-step audit found
    a bit-exact re-execution diverging, or a kvstore payload failed its
    checksum.  Classified ``"corruption"`` — its own recovery class:
    ``self_corrupt`` ranks quarantine themselves (the fleet never
    re-admits a corrupt chip), surviving majorities roll back to the
    last *verified* checkpoint (``verified_step`` — the newest all-agree
    fingerprint vote, carried by the capsule so it is provable)."""

    def __init__(self, message, step=0, minority=(), verified_step=0,
                 surface="train", self_corrupt=False):
        super().__init__(message)
        self.step = int(step)
        self.minority = tuple(int(m) for m in minority)
        self.verified_step = int(verified_step)
        self.surface = str(surface)
        self.self_corrupt = bool(self_corrupt)


class WatchdogTimeout(WorkerFailure):
    """A supervised region overran its deadline (hung collective, stalled
    compile, dead peer).  Subclasses ``WorkerFailure`` so existing
    barrier/elastic handling treats it identically — transient."""


# the transient class: faults a bounded in-process restart can survive.
# ChaosCrash is the *simulated* process death — a real one would be
# restarted by the launcher and resume from the same manifest, so the
# in-process supervisor treats it the same way.
TRANSIENT_EXCEPTIONS = (OSError, ConnectionError, TimeoutError,
                        WorkerFailure, ChaosCrash)


def classify(exc, transient=TRANSIENT_EXCEPTIONS):
    """Sort a failure into ``"transient"`` / ``"numeric"`` /
    ``"corruption"`` / ``"fatal"``.

    The classification IS the retry policy (docs/robustness.md): transient
    faults restart from the manifest, numeric divergence rolls back to the
    last verified checkpoint, data corruption (parallel/integrity.py)
    quarantines the corrupt rank or rolls survivors back to the last
    fingerprint-*verified* checkpoint, and everything else — programming
    errors, ``KeyboardInterrupt``/``SystemExit`` — propagates immediately.

    With a fleet attached, :meth:`Supervisor.run` refines one case: a
    transient ``WorkerFailure`` that coincides with a moved membership
    epoch is re-classified ``"membership"`` — reshard to the new world
    size without burning the restart budget (docs/robustness.md
    "Elastic fleets").
    """
    if isinstance(exc, DataCorruption):
        return "corruption"
    if isinstance(exc, NumericDivergence):
        return "numeric"
    if isinstance(exc, (KeyboardInterrupt, SystemExit, GeneratorExit)):
        return "fatal"
    if isinstance(exc, transient):
        return "transient"
    return "fatal"


def _recompile_count():
    """Current value of the global ``train_step.recompiles`` counter (the
    default recompile-aware grace signal: it increments at jit-build
    *entry*, so a timeout during a long compile sees it already moved)."""
    m = _telemetry.get("train_step.recompiles")
    return m.value if m is not None else 0


def run_with_deadline(fn, deadline, name="step", grace=0.0,
                      grace_signal=None, message=None):
    """Run ``fn()`` on a daemon thread and join with ``deadline`` seconds —
    `elastic.barrier`'s thread-join pattern, generalized.

    Returns ``fn``'s result; ``fn``'s own exception is re-raised in the
    caller.  If the deadline expires, first consult ``grace_signal`` (a
    zero-arg callable sampled before the call): when it changed — e.g. a
    jit recompile started during the step — wait up to ``grace`` more
    seconds before giving up.  A true timeout increments the
    ``supervisor.watchdog_fires`` counter and raises
    :class:`WatchdogTimeout`, leaving the hung daemon thread parked (a
    dead collective cannot be cancelled — the thread dies with the
    process, exactly like ``elastic.barrier``'s).

    ``deadline=None`` calls ``fn`` inline (watchdog off)."""
    if deadline is None:
        return fn()
    box = {}
    done = threading.Event()

    def _run():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed to the caller
            box["error"] = e
        finally:
            done.set()

    sig0 = grace_signal() if grace_signal is not None else None
    t = threading.Thread(target=_run, daemon=True, name=f"watchdog-{name}")
    t.start()
    if not done.wait(deadline):
        in_grace = (grace and grace_signal is not None
                    and grace_signal() != sig0)
        if in_grace:
            log.warning(
                "watchdog: %s past its %.1fs deadline with a recompile in "
                "flight — granting %.1fs compile grace", name, deadline,
                grace)
        if not (in_grace and done.wait(grace)) and not done.is_set():
            _telemetry.counter("supervisor.watchdog_fires").inc()
            _tracing.emit("supervisor.watchdog_fire", name=str(name),
                          deadline_seconds=float(deadline))
            raise WatchdogTimeout(
                message or f"watchdog: {name} hung past its "
                f"{deadline:.1f}s deadline (stalled collective or compile) "
                "— treating the step as a dead worker")
    if "error" in box:
        raise box["error"]
    return box.get("value")


class NumericSentinel:
    """NaN/Inf + loss-spike + grad-norm detection with a bounded skip
    budget.

    ``observe(loss, grad_norm=None)`` returns ``"ok"``, ``"skip"`` (bad,
    but within the ``skip_limit`` consecutive-bad budget) or ``"diverge"``
    (budget exhausted — roll back).  Spike detection compares ``|loss|``
    against ``spike_factor ×`` the median of the last ``window`` good
    losses (off by default: pass ``spike_factor``); it needs ≥5 good
    samples of history before arming, so warmup noise never trips it.
    ``skip_limit=0`` escalates on the first bad batch."""

    def __init__(self, skip_limit=2, spike_factor=None, window=32,
                 max_grad_norm=None):
        self.skip_limit = int(skip_limit)
        self.spike_factor = spike_factor
        self.max_grad_norm = max_grad_norm
        self._recent = deque(maxlen=int(window))
        self._consecutive_bad = 0
        self.last_good = None

    def reset(self):
        """Forget history + the bad streak (after a rollback: the restored
        weights invalidate both)."""
        self._recent.clear()
        self._consecutive_bad = 0

    def state_dict(self):
        """The skip ledger — capsules carry it (docs/robustness.md
        "Deterministic resume") so a resumed run's spike baseline and
        bad-streak position match the uninterrupted run's."""
        return {"recent": [float(v) for v in self._recent],
                "consecutive_bad": int(self._consecutive_bad),
                "last_good": self.last_good}

    def load_state_dict(self, state):
        self._recent.clear()
        self._recent.extend(float(v) for v in state.get("recent", ()))
        self._consecutive_bad = int(state.get("consecutive_bad", 0))
        lg = state.get("last_good")
        self.last_good = None if lg is None else float(lg)

    def _why_bad(self, loss, grad_norm):
        if loss is not None and not math.isfinite(loss):
            return f"loss={loss}"
        if grad_norm is not None:
            if not math.isfinite(grad_norm):
                return f"grad_norm={grad_norm}"
            if self.max_grad_norm and grad_norm > self.max_grad_norm:
                return (f"grad_norm={grad_norm:.3g} > "
                        f"max_grad_norm={self.max_grad_norm:.3g}")
        if (loss is not None and self.spike_factor
                and len(self._recent) >= 5):
            baseline = sorted(abs(v) for v in self._recent)[
                len(self._recent) // 2]
            if baseline > 0 and abs(loss) > self.spike_factor * baseline:
                return (f"loss spike |{loss:.3g}| > {self.spike_factor:g}× "
                        f"median {baseline:.3g}")
        return None

    def observe(self, loss, grad_norm=None):
        why = self._why_bad(loss, grad_norm)
        if why is None:
            self._consecutive_bad = 0
            if loss is not None:
                self._recent.append(float(loss))
                self.last_good = float(loss)
            return "ok"
        self._consecutive_bad += 1
        if self._consecutive_bad > self.skip_limit:
            log.error("numeric sentinel: %s — %d consecutive bad batches "
                      "exceed skip_limit=%d, declaring divergence",
                      why, self._consecutive_bad, self.skip_limit)
            return "diverge"
        log.warning("numeric sentinel: %s — skipping batch (%d/%d of the "
                    "skip budget)", why, self._consecutive_bad,
                    self.skip_limit)
        return "skip"


def _observable(value):
    """Extract the sentinel observable from a step's return value: a
    ``(loss, grad_norm)`` float pair.  Scalars/arrays reduce via mean (a
    single NaN poisons the mean — exactly the property the sentinel
    needs); a 2-tuple is ``(loss, grad_norm)``; None or non-numeric
    returns disable the numeric check for that step."""
    grad_norm = None
    if isinstance(value, tuple) and len(value) == 2:
        value, gn = value
        grad_norm = _scalar(gn)
    return _scalar(value), grad_norm


def _scalar(value):
    if value is None:
        return None
    import numpy as np
    if hasattr(value, "asnumpy"):          # NDArray (device sync: one per
        value = value.asnumpy()            # supervised step, documented)
    try:
        arr = np.asarray(value, dtype=np.float64)
    except (TypeError, ValueError):
        return None
    if arr.size == 0:
        return None
    return float(arr) if arr.size == 1 else float(np.mean(arr))


class SupervisorResult:
    """Structured exit status of a supervised run (``status`` is
    ``"completed"`` or ``"degraded"``; ``ok`` is the boolean view)."""

    def __init__(self, status, begin_epoch, num_epoch, last_epoch,
                 restarts, rollbacks, batches_skipped, watchdog_fires,
                 final_loss, reason=None):
        self.status = status
        self.begin_epoch = begin_epoch
        self.num_epoch = num_epoch
        self.last_epoch = last_epoch
        self.restarts = restarts
        self.rollbacks = rollbacks
        self.batches_skipped = batches_skipped
        self.watchdog_fires = watchdog_fires
        self.final_loss = final_loss
        self.reason = reason

    @property
    def ok(self):
        return self.status == "completed"

    def as_dict(self):
        return dict(self.__dict__)

    def __repr__(self):
        return f"SupervisorResult({self.as_dict()})"


class Supervisor:
    """The self-healing training loop driver.

    ``save_fn(epoch)`` must be a *durable* saver (manifest-committing, e.g.
    ``elastic.save_checkpoint`` / ``module.save_checkpoint``); it runs
    after every successful epoch and once more on degradation.
    ``restore_fn()`` must restore the newest verified checkpoint and
    return the epoch to resume FROM (``elastic.auto_resume``'s contract;
    0 = fresh).  Either may be None — recovery then re-enters the current
    epoch with whatever state is live (documented-lossy, but still turns
    hangs into bounded retries).

    ``deadline``/``compile_grace`` arm the hung-step watchdog (None = off).
    ``max_restarts``/``max_rollbacks`` bound the whole ``run()``;
    exhaustion degrades gracefully instead of looping forever.  See the
    module docstring for the failure classification.

    ``capsule`` (a ``resume.CapsuleManager``) makes recovery
    *deterministic* (docs/robustness.md "Deterministic resume"): every
    epoch save also commits a training-state capsule (RNG streams, data
    cursors, sentinel ledger) and, when the manager has a step interval,
    a rolling mid-epoch step capsule — restarts and rollbacks then resume
    at the exact batch with the exact RNG stream instead of re-feeding or
    skipping data.

    ``blackbox`` (a checkpoint prefix) arms the flight recorder's crash
    black box (docs/observability.md): every restart, rollback and
    degrade dumps the last-N-steps event timeline, a telemetry snapshot
    and an environment fingerprint to ``<prefix>-blackbox.json`` through
    ``checkpoint.atomic_write``; render it with
    ``tools/blackbox_report.py``."""

    def __init__(self, save_fn=None, restore_fn=None, *, deadline=None,
                 compile_grace=120.0, max_restarts=3, max_rollbacks=3,
                 skip_limit=2, spike_factor=None, window=32,
                 max_grad_norm=None, cooldown=0.0, backoff=0.5,
                 max_backoff=30.0, jitter=0.5, transient=None, resume=True,
                 seed=None, on_degraded=None, capsule=None, blackbox=None,
                 fleet=None, integrity=None):
        self.save_fn = save_fn
        self.restore_fn = restore_fn
        # SDC defense (parallel/integrity.py, docs/robustness.md "Silent
        # data corruption defense"): an IntegrityMonitor whose
        # on_committed_step runs at every step boundary — publish the
        # step's device fingerprint on its K-step cadence, vote against
        # the cohort, and raise DataCorruption on disagreement (caught
        # and classified "corruption" below)
        self.integrity = integrity
        # elastic fleet membership (parallel/fleet.py, docs/robustness.md
        # "Elastic fleets"): when attached, every step boundary runs the
        # fleet duty cycle (heartbeat + membership check) and a
        # WorkerFailure that coincides with a moved membership epoch is
        # classified "membership" — reshard via restore_fn, no restart
        # budget burned
        self.fleet = fleet
        # flight-recorder black box (docs/observability.md): a checkpoint
        # prefix; every recovery decision and degrade dumps the last-N-
        # steps timeline + telemetry snapshot to <prefix>-blackbox.json
        self.blackbox = blackbox
        self.deadline = deadline
        self.compile_grace = compile_grace
        self.max_restarts = int(max_restarts)
        self.max_rollbacks = None if max_rollbacks is None \
            else int(max_rollbacks)
        self.cooldown = float(cooldown)
        self.backoff = float(backoff)
        self.max_backoff = float(max_backoff)
        self.jitter = float(jitter)
        self.transient = tuple(transient) if transient \
            else TRANSIENT_EXCEPTIONS
        self.resume = bool(resume)
        self.on_degraded = on_degraded
        self._rng = random.Random(seed)
        self._sentinel = NumericSentinel(skip_limit=skip_limit,
                                         spike_factor=spike_factor,
                                         window=window,
                                         max_grad_norm=max_grad_norm)
        self._epoch = None
        self.restarts = 0
        self.rollbacks = 0
        self.corruptions = 0
        self.batches_skipped = 0
        self.watchdog_fires = 0
        self.steps = 0               # committed steps across the whole run
        self._step_in_epoch = 0      # committed steps in the current epoch
        self._pending_resume = None  # (epoch, step) armed by a capsule
        self.capsule = None
        if capsule is not None:
            self.attach_capsule(capsule)
        # bumped on every restore: step functions with side effects can
        # compare it across their own run to detect that a restore
        # superseded them while they ran on an abandoned watchdog thread
        # (CompiledTrainStep does this internally; module.fit's
        # sentinel_batch gates update() on it)
        self.generation = 0

    @property
    def sentinel(self):
        """The numeric sentinel (its ``state_dict`` is the skip ledger
        capsules carry)."""
        return self._sentinel

    @property
    def step_in_epoch(self):
        """Committed steps in the current epoch (the capsule loop cursor)."""
        return self._step_in_epoch

    def attach_capsule(self, manager):
        """Wire a ``resume.CapsuleManager`` to this supervisor (also sets
        the manager's back-reference); returns the manager."""
        self.capsule = manager
        manager.supervisor = self
        return manager

    def resume_step(self, epoch):
        """Steps of ``epoch`` already committed by a mid-epoch capsule
        restore (0 = start the epoch fresh).  Epoch functions use it to
        decide whether to ``reset()`` their data iterator: nonzero means
        the iterator was repositioned at the exact next batch and a reset
        would re-feed the epoch head."""
        pend = self._pending_resume
        if pend is not None and pend[0] == int(epoch):
            return pend[1]
        return 0

    # -- one supervised step ------------------------------------------------
    def step(self, fn, name=None):
        """Run one training step under the watchdog + chaos hooks + numeric
        sentinel; returns ``fn``'s value.

        ``fn``'s return feeds the sentinel: a scalar/array loss (arrays
        reduce via mean), optionally ``(loss, grad_norm)``; None skips the
        numeric check.  Chaos's ``hang_step`` fires inside the watchdog
        thread (before ``fn``), ``nan_after`` poisons the observed loss.

        With a fleet attached, the step boundary is ALSO the membership
        quiesce point: ``fleet.on_step()`` beats the heartbeat, fires a
        pending chaos preemption, and raises ``MembershipChange`` (a
        WorkerFailure) when the membership epoch moved — so the reshard
        always happens between steps, never mid-collective."""
        from .contrib import chaos

        # stamp the trace context BEFORE anything can fail: every event
        # this step emits — chaos injections, watchdog fires (on the
        # watchdog thread: the context is process-global by design),
        # phase timings, the classification — carries the in-flight
        # step's (epoch, step, generation) identity
        _tracing.set_context(epoch=self._epoch,
                             step=self._step_in_epoch + 1,
                             generation=self.generation)
        if self.fleet is not None:
            self.fleet.on_step()

        def call():
            chaos.maybe_hang()
            value = fn()
            # extract the observable INSIDE the watchdog thread: jax
            # dispatch is async, so fn() returning proves nothing — the
            # device read below is where a hung collective actually
            # blocks, and it must block on the watchdog's thread, not the
            # supervisor's
            t_read = time.perf_counter()
            obs = _observable(value)
            _tracing.emit("train_step.phase", t0=t_read,
                          t1=time.perf_counter(), phase="loss_readback")
            return value, obs

        try:
            value, (loss, grad_norm) = run_with_deadline(
                call, self.deadline,
                name=name or f"step@epoch{self._epoch}",
                grace=self.compile_grace or 0.0,
                grace_signal=_recompile_count)
        except WatchdogTimeout:
            self.watchdog_fires += 1
            raise
        if loss is not None:
            loss = chaos.poison_loss(loss)
            verdict = self._sentinel.observe(loss, grad_norm=grad_norm)
            if verdict == "skip":
                self.batches_skipped += 1
                _telemetry.counter("supervisor.batches_skipped").inc()
                _tracing.emit(
                    "supervisor.sentinel_skip", loss=float(loss),
                    consecutive_bad=int(self._sentinel._consecutive_bad))
            elif verdict == "diverge":
                raise NumericDivergence(
                    f"training diverged at epoch {self._epoch} "
                    f"(loss={loss}, grad_norm={grad_norm}) — rolling back "
                    "to the last verified checkpoint")
        # the step is committed (its batch consumed, its update — or
        # documented skip — applied): advance the loop cursor, let the
        # capsule snapshot the exact post-step state, and only THEN give
        # chaos its crash-after-commit point (crash_at_step), so a capsule
        # resume continues at the next batch, never re-feeding this one
        self._step_in_epoch += 1
        self.steps += 1
        # the integrity duty cycle runs BEFORE the capsule snapshot so a
        # verified-step advance from an all-agree vote rides this step's
        # capsule; a disagreeing vote raises DataCorruption right here —
        # the step boundary, the same quiesce point membership uses
        if self.integrity is not None:
            self.integrity.on_committed_step(self.steps)
        if self.capsule is not None:
            self.capsule.on_step(self)
        chaos.maybe_crash_step()
        return value

    # -- the supervised loop ------------------------------------------------
    def run(self, epoch_fn, begin_epoch=0, num_epoch=1):
        """Drive ``epoch_fn(epoch)`` from ``begin_epoch`` to ``num_epoch``
        with recovery; returns a :class:`SupervisorResult`.

        ``epoch_fn`` runs one epoch, calling :meth:`step` per batch.  After
        each successful epoch ``save_fn(epoch)`` commits the checkpoint;
        failures from either are classified and recovered (or propagate,
        if fatal).  A recovered run re-enters at the epoch
        ``restore_fn()`` returns — the poisoned/interrupted epoch was
        never saved, so rollback always lands on the last *good* one."""
        from .contrib import chaos
        chaos.configure_from_env()  # arm TPUMX_CHAOS faults for the run
        epoch = int(begin_epoch)
        if self.resume and self.restore_fn is not None:
            resumed = int(self.restore_fn() or 0)
            if self.capsule is not None:
                # a step capsule (fresh process resuming a crashed one)
                # repositions RNG/data/train-state at the exact batch
                resumed = self.capsule.restore(self, resumed)
            if resumed > epoch:
                log.info("supervisor: resuming from checkpointed epoch %d "
                         "(requested begin_epoch=%d)", resumed, epoch)
            epoch = max(epoch, resumed)
        _telemetry.gauge("supervisor.degraded").set(0)
        while epoch < int(num_epoch):
            self._epoch = epoch
            self._step_in_epoch = self.resume_step(epoch)
            _tracing.set_context(epoch=epoch, step=self._step_in_epoch,
                                 generation=self.generation)
            try:
                epoch_fn(epoch)
                self._pending_resume = None
                if self.save_fn is not None:
                    self.save_fn(epoch)
                if self.capsule is not None:
                    self.capsule.on_epoch(epoch, self)
            except BaseException as e:  # noqa: BLE001 — classified below
                kind = classify(e, self.transient)
                if (kind == "transient" and self.fleet is not None
                        and isinstance(e, WorkerFailure)
                        and self.fleet.poll_changed()):
                    # a WorkerFailure coinciding with a moved membership
                    # epoch is a FLEET event, not a fault — whether it
                    # surfaced as the step-boundary MembershipChange or
                    # as a dead peer's collective/barrier timeout.  It
                    # does not burn the restart budget: re-entry requires
                    # a fresh (monotone) generation, so no loop
                    kind = "membership"
                # the classification IS the supervisor's decision: it goes
                # on the timeline under the FAILING step's trace context
                # (the context advances only at the next step/epoch top,
                # so the restart/rollback events below — emitted after the
                # restore — still share it; that shared (epoch, step,
                # generation) is what lets the black box link
                # injection → detection → decision)
                _tracing.emit("supervisor.classify", kind=kind,
                              error=type(e).__name__,
                              message=str(e)[:300])
                if kind == "fatal":
                    log.error("supervisor: fatal %s at epoch %d — "
                              "propagating (programming errors are not "
                              "retried): %s", type(e).__name__, epoch, e)
                    raise
                if kind == "membership":
                    from .parallel.fleet import note_reshard
                    prev_world = self.fleet.acked_world_size
                    ep_rec = self.fleet.ack()
                    log.warning(
                        "supervisor: membership epoch %d (world size "
                        "%d -> %d, %s) — quiescing and resharding from "
                        "the last verified manifest",
                        ep_rec["generation"], prev_world,
                        ep_rec["world_size"], ep_rec.get("reason"))
                    # restore_fn is fleet-aware: it rebuilds the mesh at
                    # fleet.shard()'s world size and drives the
                    # load_state_dict reshard seam; the capsule then
                    # re-partitions the data stream from its GLOBAL
                    # cursor (resume.py capsule v2)
                    epoch = self._restore(epoch)
                    note_reshard(prev_world, ep_rec["world_size"],
                                 source="manifest",
                                 generation=ep_rec["generation"])
                    self._dump_blackbox(
                        f"membership epoch {ep_rec['generation']}: world "
                        f"{prev_world} -> {ep_rec['world_size']} "
                        f"({ep_rec.get('reason')}) — resharded, resuming "
                        f"epoch {epoch}")
                elif kind == "corruption":
                    self.corruptions += 1
                    _telemetry.counter("supervisor.corruptions").inc()
                    if getattr(e, "self_corrupt", False):
                        # THIS replica is the corrupt one (voted-out
                        # minority, or a self-attributed shadow-audit
                        # mismatch): quarantine the rank permanently —
                        # the fleet must never re-admit a flaky chip —
                        # and die loudly.  No retry: re-running on bad
                        # silicon is how silent corruption spreads.
                        if self.fleet is not None \
                                and self.fleet.member is not None:
                            try:
                                self.fleet.quarantine(
                                    self.fleet.member,
                                    reason=str(e)[:300],
                                    step=getattr(e, "step", 0))
                            except Exception as qerr:  # noqa: BLE001
                                log.error("supervisor: quarantine record "
                                          "failed: %s", qerr)
                        log.error("supervisor: %s — this rank is "
                                  "quarantined, exiting", e)
                        self._dump_blackbox(
                            f"{type(e).__name__}: {e} — rank quarantined "
                            f"(self_corrupt)")
                        _telemetry.flush()
                        raise
                    # surviving majority: the corrupt peer's gradients
                    # reached every replica through sync, so the live
                    # state is suspect past the last VERIFIED step —
                    # numeric-style rollback (the step capsule holds the
                    # poisoned trajectory and is discarded)
                    self.rollbacks += 1
                    _telemetry.counter("supervisor.rollbacks").inc()
                    if self.max_rollbacks is not None \
                            and self.rollbacks > self.max_rollbacks:
                        return self._degrade(epoch, e, "rollbacks")
                    log.warning(
                        "supervisor: %s — rolling back to the last "
                        "verified checkpoint (fingerprint-verified step "
                        "%d)", e, getattr(e, "verified_step", 0))
                    self._sentinel.reset()
                    epoch = self._restore(epoch, kind="numeric")
                    _tracing.emit(
                        "integrity.rollback",
                        step=int(getattr(e, "step", 0)),
                        verified_step=int(getattr(e, "verified_step", 0)),
                        resume_epoch=int(epoch))
                    self._dump_blackbox(
                        f"{type(e).__name__}: {e} — corruption rollback "
                        f"{self.rollbacks}/{self.max_rollbacks} to epoch "
                        f"{epoch} (verified step "
                        f"{getattr(e, 'verified_step', 0)})")
                    if self.cooldown:
                        time.sleep(self.cooldown)
                elif kind == "numeric":
                    self.rollbacks += 1
                    _telemetry.counter("supervisor.rollbacks").inc()
                    if self.max_rollbacks is not None \
                            and self.rollbacks > self.max_rollbacks:
                        return self._degrade(epoch, e, "rollbacks")
                    log.warning("supervisor: %s — rollback %d/%s, cooldown "
                                "%.1fs", e, self.rollbacks,
                                self.max_rollbacks, self.cooldown)
                    self._sentinel.reset()
                    epoch = self._restore(epoch, kind="numeric")
                    _tracing.emit("supervisor.rollback", n=self.rollbacks,
                                  resume_epoch=int(epoch))
                    self._dump_blackbox(
                        f"{type(e).__name__}: {e} — rollback "
                        f"{self.rollbacks}/{self.max_rollbacks} to "
                        f"epoch {epoch}")
                    if self.cooldown:
                        time.sleep(self.cooldown)
                else:  # transient
                    self.restarts += 1
                    _telemetry.counter("supervisor.restarts").inc()
                    if self.restarts > self.max_restarts:
                        return self._degrade(epoch, e, "restarts")
                    sleep = min(self.max_backoff,
                                self.backoff * 2 ** (self.restarts - 1))
                    sleep *= 1.0 + self.jitter * self._rng.random()
                    log.warning("supervisor: transient %s at epoch %d — "
                                "restart %d/%d after %.2fs backoff: %s",
                                type(e).__name__, epoch, self.restarts,
                                self.max_restarts, sleep, e)
                    time.sleep(sleep)
                    epoch = self._restore(epoch)
                    _tracing.emit("supervisor.restart", n=self.restarts,
                                  backoff_seconds=float(sleep),
                                  resume_epoch=int(epoch))
                    self._dump_blackbox(
                        f"{type(e).__name__}: {e} — restart "
                        f"{self.restarts}/{self.max_restarts} from "
                        f"epoch {epoch}")
                _telemetry.flush()
            else:
                epoch += 1
                _telemetry.flush()
        return self._result("completed", begin_epoch, num_epoch,
                            int(num_epoch) - 1)

    def _restore(self, current, kind="transient"):
        """Re-enter at the last verified checkpoint; without a restore_fn,
        retry the current epoch on live state (lossy — documented).

        With a capsule manager, the restore is *deterministic*: a usable
        step capsule resumes at the exact batch (transient faults only —
        a numeric rollback discards it, since it holds the trajectory
        that diverged), an epoch capsule at the epoch boundary with the
        exact RNG stream."""
        self.generation += 1  # invalidate any watchdog-abandoned step
        self._pending_resume = None
        if self.restore_fn is None:
            log.warning("supervisor: no restore_fn — retrying epoch %d on "
                        "live (possibly mid-step) state", current)
            return current
        resume_from = int(self.restore_fn() or 0)
        if self.capsule is not None:
            resume_from = self.capsule.restore(
                self, resume_from, use_step=(kind != "numeric"))
        log.warning("supervisor: restored; resuming from epoch %d%s",
                    resume_from,
                    (f" at step {self._pending_resume[1]}"
                     if self._pending_resume else ""))
        return resume_from

    def _degrade(self, epoch, err, budget):
        """Recovery budget exhausted: one clean durable final save, degraded
        gauge up, structured status out — never an unbounded crash loop.

        A NUMERIC exhaustion must NOT save: the live weights just produced
        the divergence, and committing them would make the poisoned state
        the newest verified epoch — the next resume would land exactly
        there, defeating rollback-to-last-good.  The last good checkpoint
        is already durable; restore onto it instead so the process at
        least exits on sane state."""
        _telemetry.gauge("supervisor.degraded").set(1)
        log.error("supervisor: %s budget exhausted at epoch %d (%s: %s) — "
                  "entering degraded shutdown",
                  budget, epoch, type(err).__name__, err)
        _tracing.emit("supervisor.degrade", budget=budget,
                      error=f"{type(err).__name__}: {err}"[:300])
        if classify(err, self.transient) in ("numeric", "corruption"):
            # corruption exhaustion is numeric-shaped: the live weights
            # are suspect, committing them would crown poisoned state
            if self.restore_fn is not None:
                try:
                    self.restore_fn()
                except Exception as restore_err:  # noqa: BLE001
                    log.error("supervisor: degraded final restore failed: "
                              "%s", restore_err)
        elif self.save_fn is not None:
            try:
                self.save_fn(epoch)
            except Exception as save_err:  # noqa: BLE001 — best effort
                log.error("supervisor: degraded final save failed too: %s",
                          save_err)
        if self.on_degraded is not None:
            self.on_degraded(self, err)
        self._dump_blackbox(f"degraded: {budget} budget exhausted "
                            f"({type(err).__name__}: {err})")
        _telemetry.flush()
        return self._result("degraded", None, None, epoch,
                            reason=f"{budget} exhausted: "
                                   f"{type(err).__name__}: {err}")

    def _dump_blackbox(self, reason):
        """Persist the flight-recorder black box (no-op without a
        ``blackbox`` prefix).  A dump failure is logged, never raised —
        forensics must not mask the fault being recorded."""
        if not self.blackbox:
            return None
        try:
            return _tracing.dump_blackbox(self.blackbox, reason=reason)
        except Exception as dump_err:  # noqa: BLE001 — best effort
            log.warning("supervisor: black-box dump failed: %s", dump_err)
            return None

    def _result(self, status, begin_epoch, num_epoch, last_epoch,
                reason=None):
        return SupervisorResult(
            status, begin_epoch, num_epoch, last_epoch, self.restarts,
            self.rollbacks, self.batches_skipped, self.watchdog_fires,
            self._sentinel.last_good, reason=reason)


class Supervise:
    """Configuration for supervised training through the high-level APIs
    (``module.fit(..., supervised=Supervise(prefix="ck"))``).

    ``prefix`` names the durable checkpoint prefix rollback resumes from;
    ``keep_last`` applies retention after each save (never pruning the
    newest verified epoch); ``save_optimizer_states`` folds the optimizer
    ``.states`` into each epoch's manifest.  ``capsule=True`` (or a
    prebuilt ``resume.CapsuleManager``) makes recovery deterministic:
    each epoch's manifest gains a training-state capsule (RNG + data
    cursor + sentinel ledger) and ``capsule_interval=N`` additionally
    writes a mid-epoch step capsule every N committed batches so restarts
    resume at the exact batch (docs/robustness.md "Deterministic
    resume"); the train iterator must implement ``state_dict`` (all
    in-tree iterators do, except the native image pipeline).  Every other
    keyword passes through to :class:`Supervisor` (``deadline=``,
    ``max_restarts=``, ``skip_limit=``, ...)."""

    def __init__(self, prefix=None, keep_last=3, save_optimizer_states=False,
                 capsule=None, capsule_interval=0, **supervisor_kwargs):
        self.prefix = prefix
        self.keep_last = keep_last
        self.save_optimizer_states = bool(save_optimizer_states)
        self.capsule = capsule
        self.capsule_interval = int(capsule_interval)
        self.supervisor_kwargs = supervisor_kwargs


def for_module(module, config, train_data=None):
    """Build a :class:`Supervisor` wired to a Module's checkpoint flow:
    saves go through ``module.save_checkpoint`` (manifest-committing, with
    retention), rollback through ``elastic.auto_resume(module=...)``.
    Called by ``BaseModule.fit(supervised=...)``, which passes the train
    iterator so a capsule-enabled config can snapshot its position."""
    if isinstance(config, dict):
        config = Supervise(**config)
    if config is True:
        config = Supervise()
    if not isinstance(config, Supervise):
        raise MXNetError(
            f"supervised= expects a supervisor.Supervise config (or a dict "
            f"of its kwargs), got {type(config).__name__}")
    if not config.prefix:
        raise MXNetError(
            "Supervise needs a checkpoint prefix: rollback-to-last-good "
            "is meaningless without a durable checkpoint to roll back to "
            "(pass supervised=Supervise(prefix='ck'))")
    from . import elastic as _elastic

    sup_kwargs = dict(config.supervisor_kwargs)
    # the flight recorder rides the checkpoint prefix by default: every
    # recovery decision leaves <prefix>-blackbox.json behind (pass
    # blackbox=None through Supervise to opt out)
    sup_kwargs.setdefault("blackbox", config.prefix)
    sup = Supervisor(**sup_kwargs)
    if config.capsule or config.capsule_interval:
        from . import resume as _resume
        if hasattr(config.capsule, "restore"):  # a prebuilt manager
            sup.attach_capsule(config.capsule)
        else:
            sup.attach_capsule(_resume.CapsuleManager(
                config.prefix,
                iters=[train_data] if train_data is not None else [],
                state=_resume.ModuleState(module),
                interval=config.capsule_interval))

    def save_fn(epoch):
        extra = []
        if sup.capsule is not None:
            # capsule BEFORE the manifest commit: it rides the epoch's
            # manifest and is size+sha256 verified with the checkpoint
            extra.append(sup.capsule.write_epoch_file(epoch, sup))
        module.save_checkpoint(
            config.prefix, epoch,
            save_optimizer_states=config.save_optimizer_states,
            extra_files=extra)
        if config.keep_last:
            _ckpt.apply_retention(config.prefix, config.keep_last,
                                  known_verified=epoch)

    def restore_fn():
        start = _elastic.auto_resume(config.prefix, module=module)
        if config.save_optimizer_states and start > 0:
            # roll the optimizer back WITH the weights: a rollback that
            # restores params but keeps the diverged momentum would
            # re-poison the clean weights on the next update
            states = f"{config.prefix}-{start - 1:04d}.states"
            loader = getattr(module, "load_optimizer_states", None)
            if loader is not None and os.path.exists(states):
                loader(states)
        return start

    sup.save_fn = save_fn
    sup.restore_fn = restore_fn
    return sup
