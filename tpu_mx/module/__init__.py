"""mx.module — legacy symbolic training API (REF:python/mxnet/module/)."""
from .module import BaseModule, BucketingModule, Module

__all__ = ["BaseModule", "Module", "BucketingModule"]
