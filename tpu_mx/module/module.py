"""mx.module — legacy symbolic training API (REF:python/mxnet/module/).

Parity surface: `Module` (bind/init_params/init_optimizer/forward/backward/
update/fit/score/predict, checkpointing), `BucketingModule` (the symbolic
PTB path, REF:python/mxnet/module/bucketing_module.py).

TPU-native design: the reference's `DataParallelExecutorGroup`
(REF:python/mxnet/module/executor_group.py) sliced the batch across a ctx
list, ran one GraphExecutor per GPU and reduced grads through KVStore.  Here
a *single* jitted executor runs SPMD: when `context` is a device list, the
module builds a 1-axis `jax.sharding.Mesh`, shards the batch over it and
replicates parameters — XLA inserts the gradient `psum` that KVStore used to
do.  Variable last-batch sizes simply retrace the jit (no `reshape` pass)."""
from __future__ import annotations

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import initializer as _init_mod
from .. import metric as _metric_mod
from .. import optimizer as _opt_mod
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..io.io import DataBatch, DataDesc
from ..ndarray import NDArray, array
from ..ndarray import ndarray as _nd_mod
from ..symbol import Symbol

__all__ = ["BaseModule", "Module", "BucketingModule"]


def _as_descs(shapes):
    out = []
    for s in shapes or []:
        if isinstance(s, DataDesc):
            out.append(s)
        else:
            name, shape = s[0], tuple(s[1])
            out.append(DataDesc(name, shape))
    return out


def _metric(m):
    if isinstance(m, _metric_mod.EvalMetric):
        return m
    return _metric_mod.create(m)


class BaseModule:
    """Shared high-level loop: fit / score / predict / forward_backward."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    # subclasses implement: bind, init_params, init_optimizer, forward,
    # backward, update, get_outputs, get_params, update_metric

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None, reset=True,
              epoch=0, batch_end_callback=None):
        eval_metric = _metric(eval_metric)
        eval_metric.reset()
        if reset:
            eval_data.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch >= num_batch:
                break
            self.forward(batch, is_train=False)
            if batch.pad:
                # strip wrapped-around pad rows so metrics see true samples
                outs = [NDArray(o._data[:o.shape[0] - batch.pad])
                        for o in self.get_outputs()]
                labels = [NDArray((l._data if isinstance(l, NDArray)
                                   else jnp.asarray(l))
                                  [:len(l) - batch.pad])
                          for l in batch.label]
                eval_metric.update(labels, outs)
            else:
                self.update_metric(eval_metric, batch.label)
            if batch_end_callback is not None:
                for cb in _as_list(batch_end_callback):
                    cb(BatchEndParam(epoch, nbatch, eval_metric, locals()))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True):
        if reset:
            eval_data.reset()
        outputs = []
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch >= num_batch:
                break
            self.forward(batch, is_train=False)
            outs = [o.copy() for o in self.get_outputs()]
            if batch.pad:
                outs = [NDArray(o._data[:o.shape[0] - batch.pad])
                        for o in outs]
            outputs.append(outs)
        if not merge_batches:
            return outputs
        n_out = len(outputs[0]) if outputs else 0
        merged = [_nd_mod.concatenate([b[i] for b in outputs], axis=0)
                  for i in range(n_out)]
        return merged[0] if n_out == 1 else merged

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, supervised=None):
        """The reference's canonical training loop
        (REF:python/mxnet/module/base_module.py fit).

        ``supervised=`` (a ``supervisor.Supervise`` config, or a dict of
        its kwargs) makes the loop self-healing: every epoch commits a
        durable checkpoint under the config's prefix, each batch runs
        under the hung-step watchdog + numeric sentinel, and transient
        faults / divergence restart or roll back from the last verified
        checkpoint instead of killing the job.  Returns the run's
        ``SupervisorResult`` (None in the plain path).  Each supervised
        batch reads back the first output for the NaN sentinel — one
        device sync per batch, the cost of the health check."""
        assert num_epoch is not None, "num_epoch must be specified"
        initializer = initializer or _init_mod.Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        eval_metric = _metric(eval_metric)
        validation_metric = (_metric(validation_metric)
                             if validation_metric is not None else eval_metric)

        def one_batch(data_batch):
            self.forward_backward(data_batch)
            self.update()

        def sentinel_batch(data_batch, sup):
            gen = sup.generation
            self.forward_backward(data_batch)
            # the supervisor's numeric-sentinel observable: mean of the
            # first output (a single NaN/Inf anywhere poisons the mean).
            # Checked BEFORE update() so a poisoned batch is genuinely
            # skipped — its gradients never reach the weights (a NaN that
            # appears only in the gradients still slips through; repeated
            # divergence then triggers the rollback path).  The generation
            # check discards a watchdog-abandoned batch that unblocks
            # after a restore: its stale gradients must not be applied
            # over the restored weights.
            obs = float(np.mean(self.get_outputs()[0].asnumpy()))
            if np.isfinite(obs) and gen == sup.generation:
                self.update()
            return obs

        def run_epoch(epoch, sup=None):
            tic = time.time()
            eval_metric.reset()
            if sup is None or not sup.resume_step(epoch):
                # a mid-epoch capsule restore repositioned train_data at
                # the exact next batch — resetting would re-feed the
                # epoch head (docs/robustness.md "Deterministic resume")
                train_data.reset()
            for nbatch, data_batch in enumerate(train_data):
                if monitor is not None:
                    monitor.tic()
                if sup is None:
                    one_batch(data_batch)
                else:
                    sup.step(lambda: sentinel_batch(data_batch, sup))
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    for cb in _as_list(batch_end_callback):
                        cb(BatchEndParam(epoch, nbatch, eval_metric, locals()))
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            if epoch_end_callback is not None:
                arg_p, aux_p = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 epoch=epoch,
                                 batch_end_callback=eval_batch_end_callback)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)

        if supervised is None:
            for epoch in range(begin_epoch, num_epoch):
                run_epoch(epoch)
            return None
        from .. import supervisor as _supervisor
        sup = _supervisor.for_module(self, supervised, train_data=train_data)
        return sup.run(lambda epoch: run_epoch(epoch, sup=sup),
                       begin_epoch=begin_epoch, num_epoch=num_epoch)

    def install_monitor(self, monitor):
        pass


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


class BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric, locals_=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals_


class Module(BaseModule):
    """Single-symbol module (REF:python/mxnet/module/module.py)."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger)
        if not isinstance(symbol, Symbol):
            raise MXNetError("Module requires a Symbol")
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        ctxs = context if context is not None else [current_context()]
        self._contexts = list(ctxs) if isinstance(ctxs, (list, tuple)) else [ctxs]
        self._fixed_param_names = set(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._optimizer = None
        self._updater_states = {}
        self._data_shapes = None
        self._label_shapes = None
        self._mesh = None
        if len(self._contexts) > 1:
            devs = np.array([c.jax_device() for c in self._contexts])
            self._mesh = Mesh(devs, ("dp",))

    # -- properties ---------------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        return list(self._data_names)

    @property
    def label_names(self):
        return list(self._label_names)

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        if not self.binded:
            raise MXNetError("module not bound")
        shapes = {d.name: d.shape for d in
                  (self._data_shapes or []) + (self._label_shapes or [])}
        _, out_shapes, _ = self._symbol.infer_shape(**shapes)
        return list(zip(self.output_names, out_shapes))

    # -- bind ---------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self._data_shapes = _as_descs(data_shapes)
        self._label_shapes = _as_descs(label_shapes)
        shapes = {d.name: d.shape
                  for d in self._data_shapes + self._label_shapes}
        req = {}
        for n in self._symbol.list_arguments():
            if n in self._data_names:
                req[n] = "write" if inputs_need_grad else "null"
            elif n in self._label_names or n in self._fixed_param_names:
                req[n] = "null"
            else:
                req[n] = grad_req if for_training else "null"
        if shared_module is not None and shared_module._exec is not None:
            # parameter sharing (BucketingModule): reuse the same NDArray
            # handles so in-place updates are visible to every bucket
            ex = self._symbol.simple_bind(self._contexts[0], grad_req=req,
                                          **shapes)
            for n in self._param_names:
                if n in shared_module._exec.arg_dict:
                    ex.arg_dict[n] = shared_module._exec.arg_dict[n]
            for n in self._aux_names:
                if n in shared_module._exec.aux_dict:
                    ex.aux_dict[n] = shared_module._exec.aux_dict[n]
            self._exec = ex
        else:
            self._exec = self._symbol.simple_bind(self._contexts[0],
                                                  grad_req=req, **shapes)
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

    # -- params -------------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("call bind before init_params")
        initializer = initializer or _init_mod.Uniform(0.01)
        preloaded = getattr(self, "_preloaded", None)
        if preloaded is not None and arg_params is None:
            arg_params, aux_params = preloaded

        def _sample(n, shape):
            # device-PRNG init when the initializer has a rule for it
            # (no host->device transfer; see docs/DIVERGENCES.md #23)
            dev = initializer.device_sample(n, shape) \
                if isinstance(initializer, _init_mod.Initializer) else None
            return dev if dev is not None else initializer(n, shape)

        for n in self._param_names:
            arr = self._exec.arg_dict[n]
            if arg_params and n in arg_params:
                self._set_param(self._exec.arg_dict, n, arg_params[n])
            else:
                if arg_params is not None and not allow_missing:
                    raise MXNetError(f"missing parameter '{n}' "
                                     "(pass allow_missing=True to initialize)")
                self._set_param(self._exec.arg_dict, n,
                                _sample(n, arr.shape))
        for n in self._aux_names:
            if aux_params and n in aux_params:
                self._set_param(self._exec.aux_dict, n, aux_params[n])
            else:
                if aux_params is not None and not allow_missing:
                    raise MXNetError(f"missing aux state '{n}' "
                                     "(pass allow_missing=True to initialize)")
                self._set_param(self._exec.aux_dict, n,
                                _sample(n, self._exec.aux_dict[n].shape))
        self.params_initialized = True

    def _set_param(self, d, name, value):
        data = value._data if isinstance(value, NDArray) else jnp.asarray(value)
        data = data.astype(d[name].dtype) if name in d else data
        if self._mesh is not None:
            data = jax.device_put(data, NamedSharding(self._mesh, P()))
        # rebind in place so shared handles (bucketing) see the update
        if name in d:
            d[name]._rebind(data)
        else:
            d[name] = NDArray(data)

    def get_params(self):
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    # -- optimizer ----------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, _opt_mod.Optimizer):
            self._optimizer = optimizer
        else:
            self._optimizer = _opt_mod.create(optimizer,
                                              **dict(optimizer_params))
        # name-keyed lr_mult/wd_mult need the index→name map (reference
        # Module passes param_idx2name into the optimizer)
        idx2name = dict(enumerate(self._param_names))
        if getattr(self._optimizer, "idx2name", None):
            self._optimizer.idx2name.update(idx2name)
        else:
            self._optimizer.idx2name = idx2name
        self._updater_states = {}
        for i, n in enumerate(self._param_names):
            w = self._exec.arg_dict[n]
            self._updater_states[n] = \
                self._optimizer.create_state_multi_precision(i, w)
        preload = getattr(self, "_preload_opt", None)
        if preload is not None:
            self.load_optimizer_states(preload)
            self._preload_opt = None
        self.optimizer_initialized = True

    # -- step ---------------------------------------------------------------
    def _shard(self, data, spec):
        if self._mesh is None:
            return data
        return jax.device_put(data, NamedSharding(self._mesh, spec))

    def forward(self, data_batch, is_train=None):
        if not self.binded or not self.params_initialized:
            raise MXNetError("bind and init_params before forward")
        if is_train is None:
            is_train = self.for_training
        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data):
            raw = arr._data if isinstance(arr, NDArray) else jnp.asarray(arr)
            feeds[name] = self._shard(raw, P("dp"))
        if data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                raw = arr._data if isinstance(arr, NDArray) else jnp.asarray(arr)
                feeds[name] = self._shard(raw, P("dp"))
        self._exec.forward(is_train=is_train,
                           **{k: NDArray(v) for k, v in feeds.items()})

    def backward(self, out_grads=None):
        self._exec.backward(out_grads=out_grads)

    def update(self):
        if not self.optimizer_initialized:
            raise MXNetError("init_optimizer before update")
        for i, n in enumerate(self._param_names):
            g = self._exec.grad_dict.get(n)
            if g is None:
                continue
            w = self._exec.arg_dict[n]
            self._updater_states[n] = self._optimizer.update_multi_precision(
                i, w, g, self._updater_states[n])

    def get_outputs(self, merge_multi_context=True):
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        if not self.inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True first")
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self.get_outputs())

    # -- checkpoint ---------------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        extra_files=()):
        """``extra_files`` — already-written sidecar files (e.g. a
        training-state capsule) to list in the epoch's manifest so they
        are verified with the checkpoint."""
        from ..model import save_checkpoint
        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg, aux,
                        extra_files=extra_files)
        if save_optimizer_states:
            from ..checkpoint import update_manifest
            states = f"{prefix}-{epoch:04d}.states"
            self.save_optimizer_states(states)
            # fold the states file into the epoch's already-committed
            # manifest so verification covers the full restore set
            update_manifest(prefix, epoch, [states])

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint
        sym, arg, aux = load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._preloaded = (arg, aux)
        mod._preload_opt = (f"{prefix}-{epoch:04d}.states"
                            if load_optimizer_states else None)
        return mod

    def save_optimizer_states(self, fname):
        import pickle
        from ..checkpoint import atomic_write
        states = {n: jax.tree.map(
            lambda x: np.asarray(x) if hasattr(x, "dtype") else x, s)
            for n, s in self._updater_states.items()}
        with atomic_write(fname) as f:
            f.write(pickle.dumps(states))

    def load_optimizer_states(self, fname):
        import pickle
        with open(fname, "rb") as f:
            states = pickle.load(f)
        self._updater_states = {
            n: jax.tree.map(
                lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x, s)
            for n, s in states.items()}


class BucketingModule(BaseModule):
    """Per-bucket executors sharing parameters — the symbolic variable-length
    path (REF:python/mxnet/module/bucketing_module.py).  Each bucket's jit
    cache is its own XLA program; parameters are the *same* NDArray handles,
    so the in-place optimizer updates are seen by every bucket."""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, fixed_param_names=None, state_names=None):
        super().__init__(logger)
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None

    @property
    def symbol(self):
        return self._curr_module.symbol

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      logger=self.logger, context=self._context,
                      fixed_param_names=self._fixed_param_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        if self.binded and not force_rebind:
            return
        self._buckets = {}   # stale buckets alias old parameter handles
        mod = self._gen_module(self._default_bucket_key)
        mod.bind(data_shapes, label_shapes, for_training, inputs_need_grad)
        self._buckets[self._default_bucket_key] = mod
        self._curr_module = mod
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """`data_shapes`/`label_shapes` may be bare shape tuples — they are
        paired with the NEW bucket's own data/label names from sym_gen."""
        if bucket_key not in self._buckets:
            default = self._buckets[self._default_bucket_key]
            mod = self._gen_module(bucket_key)
            if data_shapes and not isinstance(data_shapes[0], (DataDesc,)) \
                    and not isinstance(data_shapes[0][0], str):
                data_shapes = list(zip(mod.data_names, data_shapes))
                if label_shapes:
                    label_shapes = list(zip(mod.label_names, label_shapes))
            mod.bind(data_shapes, label_shapes, self.for_training,
                     self.inputs_need_grad, shared_module=default)
            extra = [n for n in mod._param_names
                     if n not in default._exec.arg_dict]
            if extra:
                raise MXNetError(
                    f"bucket {bucket_key!r} introduces parameters {extra} "
                    "absent from the default bucket — all parameters must "
                    "exist in the default bucket's symbol for sharing")
            mod.params_initialized = default.params_initialized
            mod._optimizer = default._optimizer
            mod._updater_states = default._updater_states
            mod.optimizer_initialized = default.optimizer_initialized
            self._buckets[bucket_key] = mod
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_params(self, **kwargs):
        self._curr_module.init_params(**kwargs)
        self.params_initialized = True

    def init_optimizer(self, **kwargs):
        self._curr_module.init_optimizer(**kwargs)
        # share optimizer across buckets
        for mod in self._buckets.values():
            mod._optimizer = self._curr_module._optimizer
            mod._updater_states = self._curr_module._updater_states
            mod.optimizer_initialized = True
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = getattr(data_batch, "bucket_key", self._default_bucket_key)
        if key != self._curr_bucket_key:
            # pass bare shapes; switch_bucket pairs them with the new
            # bucket's own input names from sym_gen
            data_shapes = [tuple(d.shape) for d in data_batch.data]
            label_shapes = ([tuple(d.shape) for d in data_batch.label]
                            if data_batch.label else None)
            self.switch_bucket(key, data_shapes, label_shapes)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        # all buckets hold the same _updater_states dict object; Module.update
        # mutates it in place, so no re-sharing is needed here
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs()

    def get_params(self):
        return self._curr_module.get_params()

    def set_params(self, arg_params, aux_params, **kwargs):
        self._curr_module.set_params(arg_params, aux_params, **kwargs)
        self.params_initialized = True

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        extra_files=()):
        self._curr_module.save_checkpoint(prefix, epoch,
                                          save_optimizer_states,
                                          extra_files=extra_files)
