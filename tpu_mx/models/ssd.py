"""SSD object detector (BASELINE config 4; REF:example/ssd/symbol/symbol_builder.py,
REF:src/operator/contrib/multibox_*.cc for the op semantics).

TPU-native design: the whole forward — backbone, multi-scale heads and
anchor generation — is one HybridBlock, so `hybridize()` compiles it to a
single XLA program with static shapes; anchors are constants folded at
trace time.  Training targets come from `mx.nd.contrib.MultiBoxTarget`
(vectorized matching), inference runs `MultiBoxDetection` (fixed-size
padded NMS) — both jit-compatible, no dynamic shapes anywhere
(SURVEY §7.3 hard-part 2)."""
from __future__ import annotations

import jax.numpy as jnp

from ..gluon import HybridBlock, nn
from ..ndarray import NDArray
from ..ndarray import contrib as _contrib
from ..ndarray import ops as F

__all__ = ["SSD", "ssd_512", "ssd_300", "SSDTrainingTargets"]


def _body_block(filters, in_channels):
    """VGG-ish downsampling block: 2×(conv-bn-relu) + pool/2."""
    blk = nn.HybridSequential()
    for j in range(2):
        blk.add(nn.Conv2D(filters, kernel_size=3, padding=1,
                          in_channels=in_channels if j == 0 else filters),
                nn.BatchNorm(in_channels=filters), nn.Activation("relu"))
    blk.add(nn.MaxPool2D(2, 2))
    return blk


def _scale_block(filters, strides=2, padding=1, in_channels=0):
    """Extra-scale block: 1×1 reduce + 3×3 conv (REF:example/ssd
    multi_layer_feature extra layers).  Default 3×3/s2/p1 halves the map
    (and keeps 1×1 maps at 1×1); the reference SSD300 tail uses
    3×3/s1/p0 valid convs instead (5→3→1)."""
    blk = nn.HybridSequential()
    blk.add(nn.Conv2D(filters // 2, kernel_size=1,
                      in_channels=in_channels),
            nn.BatchNorm(in_channels=filters // 2), nn.Activation("relu"),
            nn.Conv2D(filters, kernel_size=3, strides=strides,
                      padding=padding, in_channels=filters // 2),
            nn.BatchNorm(in_channels=filters), nn.Activation("relu"))
    return blk


class _L2NormScale(HybridBlock):
    """Per-position channel L2 normalization with a learnable per-channel
    scale, init 20.0 — the original SSD paper's conv4_3 treatment
    (REF:example/ssd/symbol/common.py multi_layer_feature's
    L2Normalization + scale)."""

    def __init__(self, channels, init_scale=20.0, **kwargs):
        super().__init__(**kwargs)
        from ..initializer import Constant
        self._channels = channels
        self.scale = self.params.get("scale", shape=(1, channels, 1, 1),
                                     init=Constant(init_scale))

    def hybrid_forward(self, F, x, scale):
        return F.L2Normalization(x, mode="channel") * scale


class VGG16ReducedFeatures(HybridBlock):
    """VGG16-reduced SSD backbone (REF:example/ssd/symbol/vgg16_reduced.py):
    conv1_1…conv5_3 with pool5 3×3/1 (keeps stride 16 beyond stage 4),
    atrous fc6 (1024, 3×3, dilation 6) and fc7 (1024, 1×1), both conv.
    forward(x) → [scaled conv4_3 (stride 8), fc7 (stride 16)] — the two
    base taps of the reference SSD-512/300 feature pyramid."""

    def __init__(self, in_channels=3, **kwargs):
        super().__init__(**kwargs)
        layers, filters = [2, 2, 3, 3, 3], [64, 128, 256, 512, 512]
        self.stages = []
        in_ch = in_channels
        for i, (num, f) in enumerate(zip(layers, filters)):
            stage = nn.HybridSequential()
            for _ in range(num):
                stage.add(nn.Conv2D(f, kernel_size=3, padding=1,
                                    in_channels=in_ch),
                          nn.Activation("relu"))
                in_ch = f
            if i < 3:
                # ceil-mode pooling matches the reference's feature-map
                # geometry (300: 75 -> 38, not 37 -> conv4_3 is 38x38 and
                # the pyramid reproduces the canonical 8732-anchor SSD300)
                stage.add(nn.MaxPool2D(2, 2, ceil_mode=True))
            # stage 4's pool (pool4) lives OUTSIDE the stage so conv4_3
            # can be tapped pre-pool; pool5 is 3x3/1 (reduced contract)
            self.stages.append(stage)
            setattr(self, f"stage{i + 1}", stage)
        self.pool4 = nn.MaxPool2D(2, 2, ceil_mode=True)
        self.pool5 = nn.MaxPool2D(3, 1, padding=1)
        self.fc6 = nn.Conv2D(1024, kernel_size=3, padding=6, dilation=6,
                             in_channels=512)
        self.fc7 = nn.Conv2D(1024, kernel_size=1, in_channels=1024)
        self.norm4 = _L2NormScale(512)

    def forward(self, x):
        x = self.stages[0](x)
        x = self.stages[1](x)
        x = self.stages[2](x)
        conv4_3 = self.stages[3](x)
        x = self.pool4(conv4_3)
        x = self.stages[4](x)
        x = self.pool5(x)
        x = F.Activation(self.fc6(x), act_type="relu")
        fc7 = F.Activation(self.fc7(x), act_type="relu")
        return [self.norm4(conv4_3), fc7]


class SSD(HybridBlock):
    """Multi-scale single-shot detector.

    forward(x) -> (anchors (1, A, 4), cls_preds (B, A, num_classes+1),
                   box_preds (B, A*4))
    """

    def __init__(self, num_classes, sizes, ratios, base_filters=(16, 32, 64),
                 scale_filters=128, num_scales=None, backbone="compact",
                 extra_specs=None, in_channels=3, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.sizes = [tuple(s) for s in sizes]
        self.ratios = [tuple(r) for r in ratios]
        n = num_scales or len(self.sizes)
        assert len(self.sizes) == len(self.ratios) == n
        self._num_anchors = [len(s) + len(r) - 1
                             for s, r in zip(self.sizes, self.ratios)]
        # backbone="compact": the fast bench backbone (2-conv BN blocks).
        # backbone="vgg16_reduced": the reference SSD backbone — TWO base
        # feature taps (scaled conv4_3 + atrous fc7), extras chained from
        # fc7 (REF:example/ssd/symbol/symbol_factory.py 'vgg16_reduced').
        if backbone not in ("compact", "vgg16_reduced"):
            raise ValueError(f"unknown backbone {backbone!r}")
        self._n_base_feats = 1
        if backbone == "vgg16_reduced":
            self.backbone = VGG16ReducedFeatures(in_channels=in_channels)
            self._n_base_feats = 2
            assert n >= 2, "vgg16_reduced yields 2 base scales"
            feat_channels = [512, 1024]  # scaled conv4_3, atrous fc7
        else:
            self.backbone = nn.HybridSequential()
            in_ch = in_channels
            for f in base_filters:
                self.backbone.add(_body_block(f, in_ch))
                in_ch = f
            feat_channels = [base_filters[-1]]
        self.scale_blocks = []
        self.cls_heads = []
        self.box_heads = []
        # per-extra (stride, padding); default s2/p1 chains (halving)
        n_extras = n - self._n_base_feats
        specs = list(extra_specs or [(2, 1)] * n_extras)
        assert len(specs) == n_extras, (specs, n_extras)
        for i in range(n):
            if i >= self._n_base_feats:
                st, pd = specs[i - self._n_base_feats]
                blk = _scale_block(scale_filters, strides=st, padding=pd,
                                   in_channels=feat_channels[-1])
                self.scale_blocks.append(blk)
                setattr(self, f"scale_{i}", blk)
                feat_channels.append(scale_filters)
            ch = nn.Conv2D(self._num_anchors[i] * (num_classes + 1),
                           kernel_size=3, padding=1,
                           in_channels=feat_channels[i])
            bh = nn.Conv2D(self._num_anchors[i] * 4, kernel_size=3, padding=1,
                           in_channels=feat_channels[i])
            self.cls_heads.append(ch)
            self.box_heads.append(bh)
            setattr(self, f"cls_head_{i}", ch)
            setattr(self, f"box_head_{i}", bh)

    def forward(self, x):
        base = self.backbone(x)
        base_list = base if isinstance(base, (list, tuple)) else [base]
        feats = None  # set at i=0; extras chain from base_list[-1]
        anchors, cls_preds, box_preds = [], [], []
        for i in range(len(self.sizes)):
            if i < len(base_list):
                feats = base_list[i]
            else:
                feats = self.scale_blocks[i - len(base_list)](feats)
            anchors.append(_contrib.MultiBoxPrior(
                feats, sizes=self.sizes[i], ratios=self.ratios[i]))
            c = self.cls_heads[i](feats)          # (B, K*(C+1), H, W)
            cls_preds.append(F.reshape(
                F.transpose(c, axes=(0, 2, 3, 1)),
                shape=(0, -1, self.num_classes + 1)))
            b = self.box_heads[i](feats)          # (B, K*4, H, W)
            box_preds.append(F.reshape(
                F.transpose(b, axes=(0, 2, 3, 1)), shape=(0, -1)))
        anchors = F.concat(*anchors, dim=1)       # (1, A, 4)
        cls_preds = F.concat(*cls_preds, dim=1)   # (B, A, C+1)
        box_preds = F.concat(*box_preds, dim=1)   # (B, A*4)
        return anchors, cls_preds, box_preds

    def detect(self, x, threshold=0.01, nms_threshold=0.45, nms_topk=400,
               force_suppress=False):
        """Inference: decode + NMS -> (B, A, 6) padded detections."""
        anchors, cls_preds, box_preds = self(x)
        cls_prob = F.softmax(cls_preds, axis=-1)          # (B, A, C+1)
        cls_prob = F.transpose(cls_prob, axes=(0, 2, 1))  # (B, C+1, A)
        return _contrib.MultiBoxDetection(
            cls_prob, box_preds, anchors, threshold=threshold,
            nms_threshold=nms_threshold, nms_topk=nms_topk,
            force_suppress=force_suppress)


class SSDTrainingTargets:
    """Target generator: wraps MultiBoxTarget with the SSD loss convention
    (REF:example/ssd/train/metric.py pattern: CE on cls, smooth-L1 on loc)."""

    def __init__(self, overlap_threshold=0.5, negative_mining_ratio=3.0,
                 negative_mining_thresh=0.5):
        self.kw = dict(overlap_threshold=overlap_threshold,
                       negative_mining_ratio=negative_mining_ratio,
                       negative_mining_thresh=negative_mining_thresh)

    def __call__(self, anchors, labels, cls_preds):
        # cls_preds (B, A, C+1) -> (B, C+1, A) for mining
        pred_t = F.transpose(cls_preds, axes=(0, 2, 1))
        return _contrib.MultiBoxTarget(anchors, labels, pred_t, **self.kw)


def ssd_512(num_classes=20, **kwargs):
    """SSD-512 anchor configuration (REF:example/ssd/symbol/symbol_factory.py
    get_config('vgg16_reduced', 512)).  Default compact backbone; pass
    backbone="vgg16_reduced" for the reference feature pyramid (scaled
    conv4_3 + atrous fc7 + chained extras)."""
    sizes = [(0.07, 0.1025), (0.15, 0.2121), (0.3, 0.3674), (0.45, 0.5196),
             (0.6, 0.6708), (0.75, 0.8216), (0.9, 0.9721)]
    # per-scale anchors [4,6,6,6,6,4,4] (REF symbol_factory 512 config)
    ratios = [(1, 2, 0.5)] + [(1, 2, 0.5, 3, 1.0 / 3)] * 4 + \
        [(1, 2, 0.5)] * 2
    return SSD(num_classes, sizes, ratios, **kwargs)


def ssd_300(num_classes=20, **kwargs):
    """SSD-300 anchor configuration (REF:example/ssd/symbol/symbol_factory
    get_config('vgg16_reduced', 300)): per-scale anchors [4,6,6,6,4,4];
    with backbone="vgg16_reduced" the reference tail geometry (stride-1
    valid convs, 38/19/10/5/3/1 maps) reproduces the canonical 8732
    anchors."""
    sizes = [(0.1, 0.141), (0.2, 0.272), (0.37, 0.447), (0.54, 0.619),
             (0.71, 0.79), (0.88, 0.961)]
    ratios = [(1, 2, 0.5)] + [(1, 2, 0.5, 3, 1.0 / 3)] * 3 + \
        [(1, 2, 0.5)] * 2
    if kwargs.get("backbone") == "vgg16_reduced" and \
            "extra_specs" not in kwargs:
        kwargs["extra_specs"] = [(2, 1), (2, 1), (1, 0), (1, 0)]
    return SSD(num_classes, sizes, ratios, **kwargs)
