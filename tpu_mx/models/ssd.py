"""SSD object detector (BASELINE config 4; REF:example/ssd/symbol/symbol_builder.py,
REF:src/operator/contrib/multibox_*.cc for the op semantics).

TPU-native design: the whole forward — backbone, multi-scale heads and
anchor generation — is one HybridBlock, so `hybridize()` compiles it to a
single XLA program with static shapes; anchors are constants folded at
trace time.  Training targets come from `mx.nd.contrib.MultiBoxTarget`
(vectorized matching), inference runs `MultiBoxDetection` (fixed-size
padded NMS) — both jit-compatible, no dynamic shapes anywhere
(SURVEY §7.3 hard-part 2)."""
from __future__ import annotations

import jax.numpy as jnp

from ..gluon import HybridBlock, nn
from ..ndarray import NDArray
from ..ndarray import contrib as _contrib
from ..ndarray import ops as F

__all__ = ["SSD", "ssd_512", "ssd_300", "SSDTrainingTargets"]


def _body_block(filters):
    """VGG-ish downsampling block: 2×(conv-bn-relu) + pool/2."""
    blk = nn.HybridSequential()
    for _ in range(2):
        blk.add(nn.Conv2D(filters, kernel_size=3, padding=1),
                nn.BatchNorm(), nn.Activation("relu"))
    blk.add(nn.MaxPool2D(2, 2))
    return blk


def _scale_block(filters):
    """Extra-scale block: 1×1 reduce + 3×3/s2 (REF:example/ssd
    multi_layer_feature extra layers).  Stride-2 conv with padding keeps
    1×1 maps at 1×1 instead of pooling to zero."""
    blk = nn.HybridSequential()
    blk.add(nn.Conv2D(filters // 2, kernel_size=1),
            nn.BatchNorm(), nn.Activation("relu"),
            nn.Conv2D(filters, kernel_size=3, strides=2, padding=1),
            nn.BatchNorm(), nn.Activation("relu"))
    return blk


class SSD(HybridBlock):
    """Multi-scale single-shot detector.

    forward(x) -> (anchors (1, A, 4), cls_preds (B, A, num_classes+1),
                   box_preds (B, A*4))
    """

    def __init__(self, num_classes, sizes, ratios, base_filters=(16, 32, 64),
                 scale_filters=128, num_scales=None, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.sizes = [tuple(s) for s in sizes]
        self.ratios = [tuple(r) for r in ratios]
        n = num_scales or len(self.sizes)
        assert len(self.sizes) == len(self.ratios) == n
        self._num_anchors = [len(s) + len(r) - 1
                             for s, r in zip(self.sizes, self.ratios)]
        self.backbone = nn.HybridSequential()
        for f in base_filters:
            self.backbone.add(_body_block(f))
        self.scale_blocks = []
        self.cls_heads = []
        self.box_heads = []
        for i in range(n):
            if i > 0:
                blk = _scale_block(scale_filters)
                self.scale_blocks.append(blk)
                setattr(self, f"scale_{i}", blk)
            ch = nn.Conv2D(self._num_anchors[i] * (num_classes + 1),
                           kernel_size=3, padding=1)
            bh = nn.Conv2D(self._num_anchors[i] * 4, kernel_size=3, padding=1)
            self.cls_heads.append(ch)
            self.box_heads.append(bh)
            setattr(self, f"cls_head_{i}", ch)
            setattr(self, f"box_head_{i}", bh)

    def forward(self, x):
        feats = self.backbone(x)
        anchors, cls_preds, box_preds = [], [], []
        for i in range(len(self.sizes)):
            if i > 0:
                feats = self.scale_blocks[i - 1](feats)
            anchors.append(_contrib.MultiBoxPrior(
                feats, sizes=self.sizes[i], ratios=self.ratios[i]))
            c = self.cls_heads[i](feats)          # (B, K*(C+1), H, W)
            cls_preds.append(F.reshape(
                F.transpose(c, axes=(0, 2, 3, 1)),
                shape=(0, -1, self.num_classes + 1)))
            b = self.box_heads[i](feats)          # (B, K*4, H, W)
            box_preds.append(F.reshape(
                F.transpose(b, axes=(0, 2, 3, 1)), shape=(0, -1)))
        anchors = F.concat(*anchors, dim=1)       # (1, A, 4)
        cls_preds = F.concat(*cls_preds, dim=1)   # (B, A, C+1)
        box_preds = F.concat(*box_preds, dim=1)   # (B, A*4)
        return anchors, cls_preds, box_preds

    def detect(self, x, threshold=0.01, nms_threshold=0.45, nms_topk=400,
               force_suppress=False):
        """Inference: decode + NMS -> (B, A, 6) padded detections."""
        anchors, cls_preds, box_preds = self(x)
        cls_prob = F.softmax(cls_preds, axis=-1)          # (B, A, C+1)
        cls_prob = F.transpose(cls_prob, axes=(0, 2, 1))  # (B, C+1, A)
        return _contrib.MultiBoxDetection(
            cls_prob, box_preds, anchors, threshold=threshold,
            nms_threshold=nms_threshold, nms_topk=nms_topk,
            force_suppress=force_suppress)


class SSDTrainingTargets:
    """Target generator: wraps MultiBoxTarget with the SSD loss convention
    (REF:example/ssd/train/metric.py pattern: CE on cls, smooth-L1 on loc)."""

    def __init__(self, overlap_threshold=0.5, negative_mining_ratio=3.0,
                 negative_mining_thresh=0.5):
        self.kw = dict(overlap_threshold=overlap_threshold,
                       negative_mining_ratio=negative_mining_ratio,
                       negative_mining_thresh=negative_mining_thresh)

    def __call__(self, anchors, labels, cls_preds):
        # cls_preds (B, A, C+1) -> (B, C+1, A) for mining
        pred_t = F.transpose(cls_preds, axes=(0, 2, 1))
        return _contrib.MultiBoxTarget(anchors, labels, pred_t, **self.kw)


def ssd_512(num_classes=20, **kwargs):
    """SSD-512 anchor configuration (REF:example/ssd/symbol/symbol_factory.py
    get_config('vgg16_reduced', 512)) over the compact backbone."""
    sizes = [(0.07, 0.1025), (0.15, 0.2121), (0.3, 0.3674), (0.45, 0.5196),
             (0.6, 0.6708), (0.75, 0.8216), (0.9, 0.9721)]
    ratios = [(1, 2, 0.5)] * 2 + [(1, 2, 0.5, 3, 1.0 / 3)] * 3 + \
        [(1, 2, 0.5)] * 2
    return SSD(num_classes, sizes, ratios, **kwargs)


def ssd_300(num_classes=20, **kwargs):
    sizes = [(0.1, 0.141), (0.2, 0.272), (0.37, 0.447), (0.54, 0.619),
             (0.71, 0.79), (0.88, 0.961)]
    ratios = [(1, 2, 0.5)] * 2 + [(1, 2, 0.5, 3, 1.0 / 3)] * 3 + \
        [(1, 2, 0.5)]
    return SSD(num_classes, sizes, ratios, **kwargs)
