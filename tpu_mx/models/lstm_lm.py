"""PTB word-level LSTM language model (BASELINE config 2;
REF:example/gluon/word_language_model/model.py shape: embed → multi-layer
LSTM → tied/untied decoder, trained with truncated BPTT)."""
from ..gluon import nn, rnn
from ..gluon.block import HybridBlock

__all__ = ["RNNModel"]


class RNNModel(HybridBlock):
    def __init__(self, mode="lstm", vocab_size=10000, num_embed=200,
                 num_hidden=200, num_layers=2, dropout=0.5, tie_weights=False,
                 **kwargs):
        super().__init__(**kwargs)
        self.drop = nn.Dropout(dropout)
        self.encoder = nn.Embedding(vocab_size, num_embed)
        if mode == "lstm":
            self.rnn = rnn.LSTM(num_hidden, num_layers, dropout=dropout,
                                input_size=num_embed)
        elif mode == "gru":
            self.rnn = rnn.GRU(num_hidden, num_layers, dropout=dropout,
                               input_size=num_embed)
        else:
            self.rnn = rnn.RNN(num_hidden, num_layers, dropout=dropout,
                               input_size=num_embed,
                               activation="relu" if mode == "rnn_relu"
                               else "tanh")
        if tie_weights:
            assert num_embed == num_hidden, "tied weights need equal dims"
            self.decoder = nn.Dense(vocab_size, flatten=False,
                                    params=self.encoder.params)
        else:
            self.decoder = nn.Dense(vocab_size, flatten=False,
                                    in_units=num_hidden)
        self._num_hidden = num_hidden

    def begin_state(self, batch_size=0):
        return self.rnn.begin_state(batch_size)

    def hybrid_forward(self, F, inputs, state=None):
        """inputs: (T, N) int tokens; returns (T, N, V) logits (+ state)."""
        emb = self.drop(self.encoder(inputs))
        if state is None:
            output = self.rnn(emb)
            output = self.drop(output)
            return self.decoder(output)
        output, state = self.rnn(emb, state)
        output = self.drop(output)
        return self.decoder(output), state
