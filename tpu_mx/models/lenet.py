"""LeNet-5 for MNIST (BASELINE config 0; REF:example/gluon/mnist/mnist.py
model shape)."""
from ..gluon import nn

__all__ = ["lenet"]


def lenet(classes=10):
    net = nn.HybridSequential()
    net.add(nn.Conv2D(20, kernel_size=5, activation="tanh"),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(50, kernel_size=5, activation="tanh"),
            nn.MaxPool2D(2, 2),
            nn.Flatten(),
            nn.Dense(500, activation="tanh"),
            nn.Dense(classes))
    return net
