"""tpu_mx.models — reference workload models (SURVEY §2.4 capability
checklist): LeNet (MNIST), model-zoo ResNets, PTB LSTM LM, BERT, SSD."""
from .lenet import lenet
from .lstm_lm import RNNModel
from .bert import (BERTEncoder, BERTModel, bert_base_config,
                   bert_data_specs, bert_sharding_rules)
from .ssd import SSD, SSDTrainingTargets, ssd_300, ssd_512
