"""BERT for pretraining — the flagship transformer (BASELINE config 3).

The reference ecosystem's BERT lives in GluonNLP but exercises only in-repo
capabilities (SURVEY §2.4): Gluon blocks, LayerNorm/gelu/Embedding/batch_dot
ops, LAMB, KVStore DP.  This implementation is TPU-first:

- bfloat16-friendly compute (LayerNorm stats in fp32, MXU matmuls in bf16),
- Megatron-style tensor-parallel sharding rules (qkv/FFN-in column-sharded on
  `tp`, output projections row-sharded, activations propagate via GSPMD),
- sequence axis ready for ring attention over `sp`
  (tpu_mx.parallel.ring_attention) — long-context path the reference lacked,
- the whole train step compiles into one XLA program via
  parallel.CompiledTrainStep.
"""
from __future__ import annotations

import functools
import math

import numpy as np

import jax.numpy as jnp

from .. import autograd
from .. import random as _random
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ndarray import ops
from ..parallel import P, attention as _attention

__all__ = ["BERTModel", "BERTEncoder", "TransformerLayer", "bert_base_config",
           "bert_sharding_rules", "bert_data_specs"]


def bert_base_config(vocab_size=30522, max_len=512):
    return dict(num_layers=12, units=768, hidden_size=3072, num_heads=12,
                vocab_size=vocab_size, max_length=max_len, dropout=0.1)


def _resolve_remat_policy(policy):
    """None, a jax.checkpoint_policies entry, or one of its names
    ("dots_saveable", "dots_with_no_batch_dims_saveable",
    "nothing_saveable", "everything_saveable", ...)."""
    if policy is None or not isinstance(policy, str):
        return policy
    import jax
    try:
        return getattr(jax.checkpoint_policies, policy)
    except AttributeError:
        raise ValueError(
            f"unknown remat policy {policy!r}; see jax.checkpoint_policies "
            f"for valid names") from None


def bert_sharding_rules():
    """Megatron TP layout (regex → PartitionSpec on (out, in) weights):
    column-parallel for qkv & FFN-in, row-parallel for the output mats."""
    return [
        (r"qkv_weight$", P("tp", None)),
        (r"qkv_bias$", P("tp")),
        (r"attnout_weight$", P(None, "tp")),
        (r"ffn1_weight$", P("tp", None)),
        (r"ffn1_bias$", P("tp")),
        (r"ffn2_weight$", P(None, "tp")),
        (r"word_embed_weight$", P(None, None)),
        # everything else (embeddings, LN, heads) replicated
    ]


def bert_data_specs():
    """(tokens, token_types, labels) enter sharded batch×sequence."""
    return (P("dp", "sp"), P("dp", "sp"), P("dp", "sp"))


class SelfAttention(HybridBlock):
    def __init__(self, units, num_heads, dropout=0.0, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._heads = num_heads
        self._dropout = dropout
        self._mesh = mesh
        self.qkv_weight = self.params.get("qkv_weight",
                                          shape=(3 * units, units))
        self.qkv_bias = self.params.get("qkv_bias", shape=(3 * units,))
        self.attnout_weight = self.params.get("attnout_weight",
                                              shape=(units, units))
        self.attnout_bias = self.params.get("attnout_bias", shape=(units,))

    def hybrid_forward(self, F, x, valid_length=None, qkv_weight=None,
                       qkv_bias=None, attnout_weight=None, attnout_bias=None):
        B, T, U = x.shape
        H, D = self._heads, U // self._heads
        qkv = F.FullyConnected(x, qkv_weight, qkv_bias,
                               num_hidden=3 * U, flatten=False)  # (B,T,3U)
        qkv = F.reshape(qkv, shape=(B, T, 3, H, D))
        qkv = F.transpose(qkv, axes=(2, 0, 3, 1, 4))             # (3,B,H,T,D)
        q = F.squeeze(F.slice_axis(qkv, axis=0, begin=0, end=1), axis=0)
        k = F.squeeze(F.slice_axis(qkv, axis=0, begin=1, end=2), axis=0)
        v = F.squeeze(F.slice_axis(qkv, axis=0, begin=2, end=3), axis=0)
        mesh = self._mesh
        # attention-prob dropout: train-mode only, keyed from the RNG stream
        # (traced key inside the functional call, eager split otherwise)
        rate = self._dropout if autograd.is_training() else 0.0
        drop_key = _random.take_key() if rate > 0.0 else None
        attn = functools.partial(_attention, mesh=mesh, causal=False,
                                 dropout_rate=rate, dropout_key=drop_key)
        if valid_length is not None:
            out = ops._apply(
                lambda qq, kk, vv, vl: attn(qq, kk, vv, valid_length=vl),
                [q, k, v, valid_length], "RingAttention")        # (B,H,T,D)
        else:
            out = ops._apply(lambda qq, kk, vv: attn(qq, kk, vv),
                             [q, k, v], "RingAttention")         # (B,H,T,D)
        out = F.reshape(F.transpose(out, axes=(0, 2, 1, 3)), shape=(B, T, U))
        return F.FullyConnected(out, attnout_weight, attnout_bias,
                                num_hidden=U, flatten=False)


class TransformerLayer(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0, mesh=None,
                 **kwargs):
        super().__init__(**kwargs)
        self.attention = SelfAttention(units, num_heads, dropout, mesh=mesh)
        self.ln1 = nn.LayerNorm(in_channels=units)
        self.ln2 = nn.LayerNorm(in_channels=units)
        self.dropout = nn.Dropout(dropout) if dropout else None
        self.ffn1_weight = self.params.get("ffn1_weight",
                                           shape=(hidden_size, units))
        self.ffn1_bias = self.params.get("ffn1_bias", shape=(hidden_size,))
        self.ffn2_weight = self.params.get("ffn2_weight",
                                           shape=(units, hidden_size))
        self.ffn2_bias = self.params.get("ffn2_bias", shape=(units,))
        self._hidden = hidden_size
        self._units = units

    def hybrid_forward(self, F, x, valid_length=None, ffn1_weight=None,
                       ffn1_bias=None, ffn2_weight=None, ffn2_bias=None):
        att = self.attention(x, valid_length)
        if self.dropout:
            att = self.dropout(att)
        x = self.ln1(x + att)
        h = F.FullyConnected(x, ffn1_weight, ffn1_bias,
                             num_hidden=self._hidden, flatten=False)
        h = F.gelu(h)
        h = F.FullyConnected(h, ffn2_weight, ffn2_bias,
                             num_hidden=self._units, flatten=False)
        if self.dropout:
            h = self.dropout(h)
        return self.ln2(x + h)


class MoETransformerLayer(HybridBlock):
    """TransformerLayer with the dense FFN swapped for a sparse MoE FFN
    (parallel.MoEFFN; above-parity — the reference has no MoE).  forward
    returns (x_out, aux_loss): the Switch load-balance term bubbles up
    through BERTEncoder/BERTModel when `moe_every` is set."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 mesh=None, num_experts=8, top_k=2, **kwargs):
        super().__init__(**kwargs)
        from ..parallel.moe import MoEFFN
        self.attention = SelfAttention(units, num_heads, dropout, mesh=mesh)
        self.ln1 = nn.LayerNorm(in_channels=units)
        self.ln2 = nn.LayerNorm(in_channels=units)
        self.dropout = nn.Dropout(dropout) if dropout else None
        self.moe = MoEFFN(units, hidden_size, num_experts, top_k=top_k)

    def hybrid_forward(self, F, x, valid_length=None):
        att = self.attention(x, valid_length)
        if self.dropout:
            att = self.dropout(att)
        x = self.ln1(x + att)
        h, aux = self.moe(x)
        if self.dropout:
            h = self.dropout(h)
        return self.ln2(x + h), aux


class BERTEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, vocab_size,
                 max_length, dropout=0.0, mesh=None, dtype="float32",
                 moe_every=0, moe_experts=8, moe_top_k=2, **kwargs):
        super().__init__(**kwargs)
        self.word_embed_weight = self.params.get(
            "word_embed_weight", shape=(vocab_size, units), dtype=dtype)
        self.pos_embed_weight = self.params.get(
            "pos_embed_weight", shape=(max_length, units), dtype=dtype)
        self.type_embed_weight = self.params.get(
            "type_embed_weight", shape=(2, units), dtype=dtype)
        self.ln = nn.LayerNorm(in_channels=units)
        self.dropout = nn.Dropout(dropout) if dropout else None
        self._moe = bool(moe_every)
        self.layers = nn.HybridSequential()
        n_moe = 0
        for i in range(num_layers):
            # moe_every=2 -> layers 1, 3, 5, ... are sparse (the GShard
            # every-other-layer convention)
            if moe_every and (i % moe_every) == moe_every - 1:
                self.layers.add(MoETransformerLayer(
                    units, hidden_size, num_heads, dropout, mesh=mesh,
                    num_experts=moe_experts, top_k=moe_top_k))
                n_moe += 1
            else:
                self.layers.add(TransformerLayer(units, hidden_size,
                                                 num_heads, dropout,
                                                 mesh=mesh))
        if moe_every and n_moe == 0:
            # fail where the misconfiguration is, not as `ce + None` deep
            # inside the user's compiled objective (the remat_policy
            # fail-at-construction style)
            raise ValueError(
                f"moe_every={moe_every} places no MoE layer in "
                f"{num_layers} layers (needs moe_every <= num_layers)")

    def hybrid_forward(self, F, tokens, token_types, valid_length=None,
                       word_embed_weight=None, pos_embed_weight=None,
                       type_embed_weight=None):
        T = tokens.shape[1]
        x = F.Embedding(tokens, word_embed_weight)
        x = x + F.Embedding(token_types, type_embed_weight)
        pos = F.slice_axis(pos_embed_weight, axis=0, begin=0, end=T)
        x = x + F.expand_dims(pos, axis=0)
        x = self.ln(x)
        if self.dropout:
            x = self.dropout(x)
        aux_total = None
        for layer in self.layers._children.values():
            if isinstance(layer, MoETransformerLayer):
                x, aux = layer(x, valid_length)
                aux_total = aux if aux_total is None else aux_total + aux
            else:
                x = layer(x, valid_length)
        if self._moe:
            return x, aux_total
        return x


class BERTModel(HybridBlock):
    """Encoder + tied-embedding MLM head (pretraining objective).

    moe_every=N makes every Nth transformer layer a sparse
    MoETransformerLayer (GShard-style); forward then returns
    (logits, aux_loss) — add `aux_weight * aux_loss` to the objective."""

    def __init__(self, config=None, mesh=None, dtype="float32", remat=False,
                 remat_policy=None, moe_every=0, moe_experts=8, moe_top_k=2,
                 **kwargs):
        super().__init__(**kwargs)
        cfg = config or bert_base_config()
        self._cfg = cfg
        self._moe = bool(moe_every)
        self.encoder = BERTEncoder(mesh=mesh, dtype=dtype,
                                   moe_every=moe_every,
                                   moe_experts=moe_experts,
                                   moe_top_k=moe_top_k, **cfg)
        # resolve up front: a typo'd policy (or one passed with remat off)
        # must fail at construction, not silently skew a benchmark sweep
        policy = _resolve_remat_policy(remat_policy)
        if remat_policy is not None and not remat:
            raise ValueError("remat_policy given but remat=False — pass "
                             "remat=True (or drop the policy)")
        if remat:
            # checkpoint each transformer layer: activation HBM drops from
            # O(layers) to O(1) segments + per-layer boundaries, which is
            # what lets BERT-base train at batch 512/seq 128 in 16 GB.
            # remat_policy tunes the memory/FLOPs point: "dots_saveable"
            # keeps MXU outputs (recompute only the cheap elementwise
            # tail) — more HBM, less recompute; None recomputes all.
            for layer in self.encoder.layers._children.values():
                layer.remat(policy=policy)
        units = cfg["units"]
        self.mlm_dense = nn.Dense(units, flatten=False, in_units=units)
        self.mlm_ln = nn.LayerNorm(in_channels=units)
        self.mlm_bias = self.params.get("mlm_bias",
                                        shape=(cfg["vocab_size"],))
        # dtype= must mean the WHOLE model: until r5 only the three
        # embedding tables honored it — every transformer/head weight
        # stayed f32, f32 params promoted every activation, and the
        # "bf16" BERT bench silently ran f32 elementwise/attention
        # traffic (2x HBM bytes; caught by tools/dtype_audit.py).
        # LayerNorm/softmax statistics still compute in f32 internally
        # (ops.LayerNorm upcasts; attention scores are f32 by
        # preferred_element_type).
        if dtype and str(dtype) != "float32":
            self.cast(dtype)

    def hybrid_forward(self, F, tokens, token_types, valid_length=None,
                       masked_positions=None, mlm_bias=None):
        aux = None
        if self._moe:
            x, aux = self.encoder(tokens, token_types, valid_length)
        else:
            x = self.encoder(tokens, token_types, valid_length)
        if masked_positions is not None:
            # project ONLY the masked positions through the vocab head
            # (the reference-era GluonNLP pretraining contract): at 15%
            # masking this cuts the head matmul and the logits tensor
            # ~6.7x — at bench scale (B=512, T=128, V=30522) the full
            # logits alone would be ~4 GB
            x = ops._apply(
                lambda h, p: jnp.take_along_axis(
                    h, p[..., None].astype(jnp.int32), axis=1),
                [x, masked_positions], "gather_masked")        # (B,M,U)
        h = F.gelu(self.mlm_dense(x))
        h = self.mlm_ln(h)
        # tied decoder: logits = h · E^T  (one MXU matmul over vocab).
        # Logits come out in f32 whatever the model dtype — and the f32
        # must be the MXU ACCUMULATOR (preferred_element_type), not a
        # cast after the output has already rounded to bf16: log-softmax
        # over a 30k vocab is sensitive exactly at near-tied logits,
        # where bf16's ~2-3 decimal digits lose the ranking.
        embed = self.encoder.word_embed_weight.data()
        logits = ops._apply(
            lambda hh, ee, bb: jnp.einsum(
                "...u,vu->...v", hh, ee,
                preferred_element_type=jnp.float32)
            + bb.astype(jnp.float32),
            [h, embed, mlm_bias], "mlm_logits_f32")
        if self._moe:
            return logits, aux
        return logits
