"""`mx.npx` — neural-network extensions for the numpy namespace
(REF:python/mxnet/ndarray/numpy_extension/ + python/mxnet/util.py set_np).

Upstream these are separate C++ kernels re-exported under npx; here the
classic op library already IS the jax-backed implementation, so npx simply
re-exports it under the numpy-era names.  `set_np`/`is_np_array` keep the
upstream switch-semantics API; the unified NDArray means the switch only
tracks intent (documented divergence — both namespaces share one array
type, so there is nothing to toggle)."""
from __future__ import annotations

from .base import MXNetError
from .ndarray import NDArray
from .ndarray import ops as _ops

_np_active = False


def set_np(shape=True, array=True, dtype=False):
    """Upstream flips Gluon into numpy-array mode; the unified NDArray is
    always numpy-flavored, so this records intent only."""
    global _np_active
    _np_active = bool(array)


def reset_np():
    global _np_active
    _np_active = False


def is_np_array():
    return _np_active


def is_np_shape():
    return _np_active


# nn extensions: numpy-era names -> classic op library (same kernels)
activation = _ops.Activation
batch_norm = _ops.BatchNorm
convolution = _ops.Convolution
fully_connected = _ops.FullyConnected
pooling = _ops.Pooling
dropout = _ops.Dropout
embedding = _ops.Embedding
one_hot = _ops.one_hot
pick = _ops.pick
topk = _ops.topk
softmax = _ops.softmax
log_softmax = _ops.log_softmax
sigmoid = _ops.sigmoid
relu = _ops.relu
batch_dot = _ops.batch_dot
reshape_like = _ops.reshape_like
gather_nd = _ops.gather_nd
sequence_mask = _ops.SequenceMask
leaky_relu = _ops.LeakyReLU


def gelu(data, **kw):
    return _ops.gelu(data)


def load(fname):
    from .ndarray import load as _load
    return _load(fname)


def save(fname, data):
    from .ndarray import save as _save
    return _save(fname, data)


def waitall():
    from .ndarray import waitall as _waitall
    return _waitall()


def seed(s):
    from . import random as _random
    _random.seed(s)
