"""`mx.npx` — neural-network extensions for the numpy namespace
(REF:python/mxnet/ndarray/numpy_extension/ + python/mxnet/util.py set_np).

Upstream these are separate C++ kernels re-exported under npx; here the
classic op library already IS the jax-backed implementation, so npx simply
re-exports it under the numpy-era names.  `set_np`/`is_np_array` keep the
upstream switch-semantics API; the unified NDArray means the switch only
tracks intent (documented divergence — both namespaces share one array
type, so there is nothing to toggle)."""
from __future__ import annotations

from .base import MXNetError
from .ndarray import NDArray
from .ndarray import ops as _ops

_np_active = False


def set_np(shape=True, array=True, dtype=False):
    """Upstream flips Gluon into numpy-array mode; the unified NDArray is
    always numpy-flavored, so this records intent only."""
    global _np_active
    _np_active = bool(array)


def reset_np():
    global _np_active
    _np_active = False


def is_np_array():
    return _np_active


def is_np_shape():
    return _np_active


# nn extensions: numpy-era names -> classic op library (same kernels)
activation = _ops.Activation
batch_norm = _ops.BatchNorm
convolution = _ops.Convolution
fully_connected = _ops.FullyConnected
pooling = _ops.Pooling
dropout = _ops.Dropout
embedding = _ops.Embedding
one_hot = _ops.one_hot
pick = _ops.pick
topk = _ops.topk
softmax = _ops.softmax
log_softmax = _ops.log_softmax
sigmoid = _ops.sigmoid
relu = _ops.relu
batch_dot = _ops.batch_dot
reshape_like = _ops.reshape_like
gather_nd = _ops.gather_nd
sequence_mask = _ops.SequenceMask
leaky_relu = _ops.LeakyReLU


def gelu(data, **kw):
    return _ops.gelu(data)


def load(fname):
    from .ndarray import load as _load
    return _load(fname)


def save(fname, data):
    from .ndarray import save as _save
    return _save(fname, data)


def waitall():
    from .ndarray import waitall as _waitall
    return _waitall()


def seed(s):
    from . import random as _random
    _random.seed(s)


# round-3 widening: the rest of the heavily-used npx surface, each mapping
# to an existing classic op (REF:python/mxnet/numpy_extension + _api_internal
# npx registry)
from .ndarray import contrib as _contrib


def arange_like(data, start=0.0, step=1.0, axis=None, **kw):
    """arange shaped like data (REF:src/operator/tensor/init_op.cc
    arange_like): full flat length, or along one axis."""
    import jax.numpy as _jnp
    from .ndarray.ops import _apply

    def f(x):
        n = x.size if axis is None else x.shape[axis]
        out = start + step * _jnp.arange(n, dtype=_jnp.float32)
        return out.reshape(x.shape) if axis is None else out

    return _apply(f, [data], "arange_like")


batch_flatten = _ops.Flatten
broadcast_like = _ops.broadcast_like
ctc_loss = _ops.CTCLoss
deconvolution = _ops.Deconvolution
erf = _ops.erf
erfinv = _ops.erfinv
layer_norm = _ops.LayerNorm
multibox_detection = _contrib.MultiBoxDetection
multibox_prior = _contrib.MultiBoxPrior
multibox_target = _contrib.MultiBoxTarget
rnn = _ops.RNN
roi_pooling = _ops.ROIPooling
scatter_nd = _ops.scatter_nd
shape_array = _ops.shape_array
slice = _ops.slice
smooth_l1 = _ops.smooth_l1
foreach = _contrib.foreach
while_loop = _contrib.while_loop
cond = _contrib.cond
