"""mx.model — checkpoint helpers (REF:python/mxnet/model.py).

The reference pairs `<prefix>-symbol.json` with `<prefix>-NNNN.params`
(dmlc-stream serialized NDArrays, keys prefixed ``arg:``/``aux:``); the same
file layout is kept here over the framework's own NDArray save format so
Module/Gluon checkpoints round-trip byte-compatibly within this framework.
"""
from __future__ import annotations

from .ndarray import ndarray as _nd
from .symbol import Symbol, load as _sym_load

__all__ = ["save_checkpoint", "load_checkpoint", "BatchEndParam"]

from .module.module import BatchEndParam  # re-export (reference parity)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True, extra_files=()):
    """Write the epoch's symbol + params atomically, then commit the
    durability manifest LAST (tpu_mx/checkpoint.py): a crash at any point
    mid-save leaves the previous epoch as the newest verified checkpoint
    instead of a truncated .params file (docs/robustness.md).

    ``extra_files`` — already-atomically-written sidecars (e.g. the
    epoch's training-state capsule, tpu_mx/resume.py) to fold into the
    manifest's verified file table before the commit."""
    import os
    import time
    from . import checkpoint as _ckpt
    from . import telemetry as _telemetry
    from . import tracing as _tracing
    t_save = time.perf_counter()
    with _telemetry.span("checkpoint.save_seconds"):
        extra = None
        if symbol is not None:
            sym_file = f"{prefix}-symbol.json"
            symbol.save(sym_file)
            # {prefix}-symbol.json is SHARED across epochs and rewritten by
            # every save: listing it in the per-epoch manifest would flip
            # every older epoch to "corrupt" the moment the symbol changes,
            # defeating fall-back-to-older-epoch (gluon/block.py export
            # excludes it for the same reason).  Its content hash at save
            # time rides the manifest's unverified "shared" table instead,
            # so the epoch↔symbol pairing stays auditable.
            extra = {"shared": {os.path.basename(sym_file):
                                _ckpt._file_entry(sym_file)}}
        save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
        save_dict.update({f"aux:{k}": v
                          for k, v in (aux_params or {}).items()})
        params = f"{prefix}-{epoch:04d}.params"
        _nd.save(params, save_dict)
        _ckpt.write_manifest(prefix, epoch, [params, *extra_files],
                             extra=extra)
    _tracing.emit("checkpoint.save", t0=t_save, t1=time.perf_counter(),
                  prefix=os.path.basename(str(prefix)), epoch=int(epoch))


def load_checkpoint(prefix, epoch):
    symbol = _sym_load(f"{prefix}-symbol.json")
    loaded = _nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        kind, name = k.split(":", 1)
        if kind == "arg":
            arg_params[name] = v
        elif kind == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params
