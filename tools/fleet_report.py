#!/usr/bin/env python
"""Render a FLEET black box (`<fleet_dir>/fleet-blackbox.json`) as a
cross-rank post-mortem.

A supervised elastic run (`tools/launch.py --supervise`) dumps the fleet
black box on every evict/degrade decision and at supervise exit: the
ordinary flight-recorder document (tools/blackbox_report.py reads it
unchanged) EXTENDED with a ``fleet`` section — every live worker's last
shipped events + telemetry snapshot aligned on the membership
generation, the merged fleet aggregate, the cross-rank step-skew
timeline and the straggler verdict (tpu_mx/parallel/fleet_obs.py).
This tool renders that section:

- the **per-rank table**: shipped generation, last trace context, event
  and telemetry-record counts per rank;
- the **fleet aggregate**: every merged record with its per-rank value
  breakdown (counters sum, gauges spread min/mean/max);
- the **skew timeline**: per correlated step, the cross-rank skew, the
  slowest rank and the phase that explains the gap;
- the **straggler verdict** the supervisor acted on;
- the **corruption verdict**: each rank's last published state
  fingerprint, the cross-replica vote history, and every permanently
  quarantined rank with its recorded reason.

``--validate`` schema-checks the section AND re-proves the aggregation
exactness invariant from the document alone: every merged counter must
equal the sum of its ``per_rank`` breakdown, and re-merging the stored
per-rank snapshots must reproduce the stored aggregate exactly.
Exit status: 0 ok, 1 validation failure, 2 unreadable input.

Like blackbox_report/capacity_report, the tpu_mx modules are loaded
standalone from their files — this tool NEVER imports the ``tpu_mx``
package (which would boot jax) just to read a JSON post-mortem.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_module(relpath, name):
    """Load one tpu_mx module from its file WITHOUT importing the
    package (fleet_obs's merge core, telemetry and tracing are
    stdlib-only at module level by contract)."""
    path = os.path.join(REPO, *relpath.split("/"))
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fmt(v):
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def render_ranks(fl):
    lines = ["Per-rank shipped state (generation-aligned):",
             "  %-5s %-4s %-14s %8s %10s" % ("rank", "gen", "context",
                                             "events", "telemetry")]
    ranks = fl.get("ranks", {})
    if not ranks:
        return lines + ["  (no rank shipped a snapshot)"]
    for r in sorted(ranks, key=int):
        body = ranks[r]
        ctx = body.get("context", {})
        ctx_s = "e%s/s%s" % (ctx.get("epoch", "-"), ctx.get("step", "-"))
        lines.append("  %-5s %-4s %-14s %8d %10d" % (
            r, body.get("generation", "?"), ctx_s,
            len(body.get("events", [])), len(body.get("telemetry", []))))
    gap = [str(m) for m in fl.get("world", [])
           if str(m) not in ranks]
    if gap:
        lines.append(f"  MISSING (in world, nothing shipped — gap, not "
                     f"interpolated): rank(s) {', '.join(gap)}")
    return lines


def render_aggregate(fl):
    lines = ["Fleet aggregate (counters summed, gauges spread, "
             "histograms bucket-merged):"]
    agg = fl.get("aggregate", [])
    if not agg:
        return lines + ["  (empty aggregate)"]
    for rec in sorted(agg, key=lambda r: (r.get("name", ""),
                                          str(r.get("labels", {})))):
        name = rec.get("name", "?")
        labels = rec.get("labels")
        if labels:
            name += "{%s}" % ",".join(f"{k}={v}"
                                      for k, v in sorted(labels.items()))
        pr = rec.get("per_rank", {})
        pr_s = " ".join(f"r{r}={_fmt(v)}"
                        for r, v in sorted(pr.items(), key=lambda kv:
                                           int(kv[0])))
        kind = rec.get("type")
        if kind == "gauge":
            val = (f"mean={_fmt(rec.get('mean', rec.get('value')))} "
                   f"min={_fmt(rec.get('min'))} max={_fmt(rec.get('max'))}")
        elif kind == "histogram":
            val = f"count={rec.get('value')} sum={_fmt(rec.get('sum', 0.0))}"
        else:
            val = _fmt(rec.get("value"))
        lines.append("  %-46s %-34s %s" % (name, val, pr_s))
    return lines


def render_skew(fl):
    lines = ["Cross-rank step-skew timeline "
             "(correlated on (epoch, step, generation)):"]
    timeline = fl.get("skew_timeline", [])
    if not timeline:
        return lines + ["  (no step observed by >= 2 ranks)"]
    for c in timeline:
        lines.append("  g%s e%s s%-5s skew=%ss  slowest=rank %s "
                     "(dominant phase: %s)" % (
                         c.get("generation"), c.get("epoch"),
                         c.get("step"), _fmt(c.get("skew_seconds")),
                         c.get("slowest_rank"), c.get("dominant_phase")))
    return lines


def render_signal(fl):
    sig = fl.get("straggler_signal", {})
    if sig.get("straggling"):
        return [f"Straggler verdict: rank {sig.get('rank')} is a "
                f"persistent straggler — +{_fmt(sig.get('excess_seconds'))}"
                f"s/step, dominant phase {sig.get('dominant_phase')!r}, "
                f"slowest in {sig.get('steps')} of the last "
                f"{sig.get('window')} correlated steps"]
    return ["Straggler verdict: none (no rank persistently slowest)"]


def render_corruption(fl):
    corr = fl.get("corruption")
    if not isinstance(corr, dict):
        return ["Corruption verdict: (no integrity data in this dump)"]
    cv = corr.get("verdict", {})
    if cv.get("clean", False):
        head = "Corruption verdict: clean (every vote agreed, no rank " \
               "quarantined)"
    else:
        head = ("Corruption verdict: CORRUPT — mismatching vote(s) at "
                f"step(s) {cv.get('mismatch_steps')}, suspected rank(s) "
                f"{cv.get('suspected')}, quarantined {cv.get('quarantined')}")
    lines = [head]
    fps = corr.get("fingerprints", {})
    if fps:
        lines.append("  Last published fingerprints:")
        for r in sorted(fps, key=int):
            rec = fps[r]
            lines.append("    rank %-4s step %-6s fp=%#010x" % (
                r, rec.get("step", "?"), int(rec.get("fp", 0))))
    votes = corr.get("votes_by_rank", {})
    for r in sorted(votes, key=int):
        for v in votes[r]:
            if v.get("agree", True):
                continue
            lines.append(
                "    rank %s vote @ step %s: DISAGREE majority=%#010x "
                "minority=%s absent=%s" % (
                    r, v.get("step"), int(v.get("majority_fp", 0)),
                    v.get("minority"), v.get("absent")))
    for r in sorted(corr.get("quarantined", {}), key=int):
        rec = corr["quarantined"][r]
        lines.append("    rank %s QUARANTINED at step %s (gen %s): %s" % (
            r, rec.get("step", "?"), rec.get("generation", "?"),
            rec.get("reason", "?")))
    return lines


def render(doc, path):
    fl = doc.get("fleet", {})
    out = [f"Fleet black box: {path}",
           f"  format:     {doc.get('format')} + {fl.get('format')}",
           f"  reason:     {doc.get('reason') or '(unspecified)'}",
           f"  written:    {doc.get('written_at')}",
           f"  generation: {fl.get('generation')}  "
           f"world={fl.get('world')}",
           f"  reporting:  {fl.get('ranks_reporting')}  "
           f"(stale records dropped: {fl.get('stale_dropped')})", ""]
    out.extend(render_ranks(fl))
    out.append("")
    out.extend(render_signal(fl))
    out.append("")
    out.extend(render_corruption(fl))
    out.append("")
    out.extend(render_skew(fl))
    out.append("")
    out.extend(render_aggregate(fl))
    return "\n".join(out)


def validate(doc, fleet_obs, tracing, telemetry):
    """Every violation as a string (empty = valid): the base black-box
    schema, the fleet section schema, and the aggregation identity."""
    errors = []
    try:
        tracing.validate_blackbox(doc)
    except ValueError as e:
        errors.append(f"base document: {e}")
    try:
        fleet_obs.validate_fleet_section(doc, telemetry=telemetry)
    except ValueError as e:
        errors.append(f"fleet section: {e}")
    fl = doc.get("fleet")
    if isinstance(fl, dict):
        for r, body in sorted((fl.get("ranks") or {}).items()):
            for i, ev in enumerate(body.get("events") or []):
                try:
                    tracing.validate_event(ev)
                except ValueError as e:
                    errors.append(f"rank {r} event[{i}]: {e}")
            for i, rec in enumerate(body.get("telemetry") or []):
                try:
                    telemetry.validate_record(rec)
                except ValueError as e:
                    errors.append(f"rank {r} telemetry[{i}]: {e}")
                    continue
                if rec["name"] not in telemetry.KNOWN_METRICS:
                    errors.append(f"rank {r} telemetry[{i}]: unknown "
                                  f"metric {rec['name']!r}")
    return errors


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("file", help="a fleet-blackbox.json dump")
    ap.add_argument("--validate", action="store_true",
                    help="fail on schema violations or a broken "
                         "aggregation identity (merged counters must "
                         "equal their per-rank sums, and re-merging the "
                         "stored snapshots must reproduce the aggregate)")
    opts = ap.parse_args(argv)
    try:
        with open(opts.file, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"fleet_report: cannot read {opts.file}: {e}",
              file=sys.stderr)
        return 2
    fleet_obs = load_module("tpu_mx/parallel/fleet_obs.py",
                            "_tpumx_fleet_obs")
    print(render(doc, opts.file))
    if opts.validate:
        tracing = load_module("tpu_mx/tracing.py", "_tpumx_tracing")
        telemetry = load_module("tpu_mx/telemetry.py", "_tpumx_telemetry")
        errors = validate(doc, fleet_obs, tracing, telemetry)
        if errors:
            print("VALIDATION FAILED:", file=sys.stderr)
            for e in errors:
                print(f"  {e}", file=sys.stderr)
            return 1
        fl = doc.get("fleet", {})
        print(f"schema OK: {len(fl.get('ranks', {}))} rank(s), "
              f"{len(fl.get('aggregate', []))} aggregate record(s), "
              f"{len(fl.get('skew_timeline', []))} correlated step(s); "
              "aggregation identity holds")
    return 0


if __name__ == "__main__":
    sys.exit(main())
