#!/usr/bin/env python
"""Render the capacity ledger: pool timeline, top holders, forensics.

The serving runtime's capacity accounting layer (ISSUE 14;
tpu_mx/serving/accounting.py) attributes every KV block-pool byte to a
holder and a tenant, publishes the attribution as the ``serve.pool_*``
gauges on every telemetry flush, and dumps an exhaustion forensic
record — every live holder named — on each ``CacheExhausted`` and
pressure eviction.  This tool is the jax-less ops view over that data:

- **Ledger timeline**: one row per telemetry flush — pool-used bytes,
  high watermark and free-list fragmentation over the run (the
  fragmentation trend rides this table);
- **Per-tenant attribution**: the last snapshot's
  ``serve.pool_bytes{tenant,kind}`` gauges — amortized (1/refcount
  shares, sums to pool-used bytes) next to exclusive-if-forked cost —
  plus index residency, pinned blocks and host RSS;
- **Exhaustion forensics** (``--forensics <prefix>-capacity.json``):
  each recorded capacity event with its top holders — sequence/tenant,
  block counts, pinned/shared state, age — "who was holding the pool
  when backpressure hit";
- **Capacity twins**: the training-side gauges (per-shape jit compile
  count/seconds, checkpoint bytes-on-disk) when present.

``--validate`` schema-gates every telemetry record against the catalog,
re-checks the accounting identity offline (per snapshot: the amortized
per-tenant gauges must sum to ``serve.pool_used_bytes``), and validates
the forensic document against its schema — including the
100%-of-holders and per-record identity gates.  Exit status: 0 ok, 1
validation failure, 2 unreadable input — the same contract as
tools/slo_report.py and tools/blackbox_report.py.

The tpu_mx modules are loaded standalone from their files — this tool
NEVER imports the ``tpu_mx`` package (which would boot jax); it must
work on a machine with no accelerator stack at all.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# share the standalone loaders: blackbox_report loads top-level tpu_mx
# modules by file path (never the package), slo_report the JSONL series
# reader — one implementation each, no drift
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from blackbox_report import load_module  # noqa: E402
from slo_report import read_series  # noqa: E402

# tolerance for the offline identity re-check: the LIVE identity is
# exact Fraction math; each gauge rounds one tenant's share to a float
IDENTITY_RTOL = 1e-6


def load_accounting():
    """Load tpu_mx/serving/accounting.py standalone (stdlib-only by
    contract, like telemetry/tracing — its package-relative imports
    degrade to local fallbacks)."""
    path = os.path.join(REPO, "tpu_mx", "serving", "accounting.py")
    spec = importlib.util.spec_from_file_location("_tpumx_accounting", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def read_timeline(path):
    """Every ``serve.pool_*`` gauge record grouped by snapshot ``ts``,
    in file order: ``[(ts, {name: value})]`` — the ledger timeline."""
    rows = []
    by_ts = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # --validate reports it via read_series
            name = rec.get("name", "")
            if not (name.startswith("serve.pool_")
                    or name == "serve.prefix_index_bytes"):
                continue
            ts = rec.get("ts")
            if ts not in by_ts:
                by_ts[ts] = {}
                rows.append((ts, by_ts[ts]))
            labels = rec.get("labels") or {}
            key = name
            if labels:
                key += "{%s}" % ",".join(
                    f"{k}={v}" for k, v in sorted(labels.items()))
            by_ts[ts][key] = rec.get("value")
    return rows


def _mib(v):
    return "-" if v is None else f"{v / 2 ** 20:.3f}"


def render_timeline(timeline):
    lines = ["Ledger timeline (one row per telemetry flush; MiB):",
             "  %-6s %12s %12s %14s %8s" %
             ("snap", "used", "watermark", "index", "frag")]
    if not timeline:
        lines.append("  (no serve.pool_* gauges — training-only "
                     "snapshot, or a pre-ledger run)")
        return lines
    for i, (_, vals) in enumerate(timeline):
        lines.append("  %-6d %12s %12s %14s %8s" % (
            i,
            _mib(vals.get("serve.pool_used_bytes")),
            _mib(vals.get("serve.pool_high_watermark_bytes")),
            _mib(vals.get("serve.prefix_index_bytes")),
            "-" if vals.get("serve.pool_fragmentation") is None
            else f"{vals['serve.pool_fragmentation']:.3f}"))
    return lines


def tenant_rows(series):
    """{tenant: {kind: value}} from the last-snapshot pool_bytes gauges."""
    out = {}
    for (name, lj), rec in series.items():
        if name != "serve.pool_bytes":
            continue
        labels = json.loads(lj)
        tenant = labels.get("tenant", "?")
        out.setdefault(tenant, {})[labels.get("kind", "?")] = \
            rec.get("value", 0.0)
    return out


def render_tenants(series):
    tenants = tenant_rows(series)
    lines = ["Per-tenant pool attribution (last snapshot; MiB):",
             "  %-16s %14s %16s" % ("Tenant", "amortized",
                                    "exclusive-if-forked")]
    if not tenants:
        lines.append("  (no serve.pool_bytes series)")
        return lines
    for tenant in sorted(tenants,
                         key=lambda t: -tenants[t].get("amortized", 0.0)):
        d = tenants[tenant]
        lines.append("  %-16s %14s %16s" % (
            tenant, _mib(d.get("amortized")), _mib(d.get("exclusive"))))
    total = sum(d.get("amortized", 0.0) for d in tenants.values())
    used = (series.get(("serve.pool_used_bytes", "{}")) or {}).get("value")
    lines.append("  %-16s %14s %16s" % ("(sum)", _mib(total), ""))
    lines.append("  %-16s %14s %16s  <- the accounting identity"
                 % ("(pool used)", _mib(used), ""))
    return lines


def render_pool_state(series):
    def val(name):
        return (series.get((name, "{}")) or {}).get("value")

    lines = ["Pool state (last snapshot):"]
    frag = val("serve.pool_fragmentation")
    pinned = val("serve.pool_pinned_blocks")
    rss = val("host.rss_bytes")
    lines.append(f"  used {_mib(val('serve.pool_used_bytes'))} MiB, "
                 f"high watermark "
                 f"{_mib(val('serve.pool_high_watermark_bytes'))} MiB, "
                 f"prefix index {_mib(val('serve.prefix_index_bytes'))} "
                 "MiB")
    lines.append("  fragmentation "
                 + ("-" if frag is None else f"{frag:.3f}")
                 + ", pinned blocks "
                 + ("-" if pinned is None else f"{pinned:g}")
                 + ", host RSS " + _mib(rss) + " MiB")
    return lines


def render_twins(series):
    """The training-side capacity twins, when present."""
    rows = []
    for (name, lj), rec in sorted(series.items()):
        if name == "train_step.compiles":
            sig = json.loads(lj).get("signature", "?")
            rows.append(f"  jit compiles [{sig}]: {rec.get('value')}")
        elif name == "train_step.compile_seconds":
            sig = json.loads(lj).get("signature", "?")
            rows.append(f"  compile seconds [{sig}]: "
                        f"{rec.get('sum', 0.0):.3f}s over "
                        f"{rec.get('value')} build(s)")
        elif name == "checkpoint.bytes_on_disk":
            rows.append(f"  checkpoint bytes on disk: "
                        f"{_mib(rec.get('value'))} MiB")
    if not rows:
        return []
    return ["Training-side capacity twins:"] + rows


def render_forensics(doc, top):
    recs = doc.get("records", [])
    lines = [f"Exhaustion forensics ({len(recs)} recorded capacity "
             "event(s)):"]
    if not recs:
        lines.append("  (no capacity events recorded)")
        return lines
    for rec in recs:
        pool = rec.get("pool", {})
        lines.append(
            "  [%s] need=%s free=%s released=%s used=%s/%s blocks "
            "frag=%.3f" % (
                rec.get("kind"), rec.get("need"), rec.get("free"),
                rec.get("released"), pool.get("used_blocks"),
                pool.get("num_blocks"), pool.get("fragmentation", 0.0)))
        holders = sorted(rec.get("holders", []),
                         key=lambda h: -h.get("blocks", 0))
        lines.append("    %-10s %-22s %-12s %7s %6s %6s %7s %8s" % (
            "kind", "holder", "tenant", "blocks", "excl", "shared",
            "pinned", "age(s)"))
        for h in holders[:top]:
            lines.append("    %-10s %-22s %-12s %7d %6d %6d %7s %8.2f"
                         % (h.get("kind"), h.get("id"), h.get("tenant"),
                            h.get("blocks", 0),
                            h.get("exclusive_blocks", 0),
                            h.get("shared_blocks", 0),
                            "yes" if h.get("pinned") else "no",
                            h.get("age_seconds", 0.0)))
        if len(holders) > top:
            lines.append(f"    ... and {len(holders) - top} more "
                         "holder(s)")
    return lines


def validate_identity(path, telemetry):
    """Re-check the accounting identity offline, per snapshot: the
    amortized per-tenant ``serve.pool_bytes`` gauges must sum to
    ``serve.pool_used_bytes`` within float-rendering tolerance."""
    errors = []
    by_ts = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # reported by the schema pass
            name = rec.get("name")
            if name not in ("serve.pool_bytes", "serve.pool_used_bytes"):
                continue
            snap = by_ts.setdefault(rec.get("ts"), {"used": None,
                                                    "amortized": 0.0})
            if name == "serve.pool_used_bytes":
                snap["used"] = rec.get("value")
            elif (rec.get("labels") or {}).get("kind") == "amortized":
                snap["amortized"] += rec.get("value", 0.0)
    for ts, snap in by_ts.items():
        if snap["used"] is None:
            continue
        drift = abs(snap["amortized"] - snap["used"])
        if drift > max(IDENTITY_RTOL * snap["used"], 1e-6):
            errors.append(
                f"snapshot ts={ts}: per-tenant amortized bytes sum to "
                f"{snap['amortized']} but serve.pool_used_bytes is "
                f"{snap['used']} — the accounting identity is broken")
    return errors


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("file", help="TPUMX_TELEMETRY JSONL snapshot file")
    ap.add_argument("--forensics", default=None,
                    help="a <prefix>-capacity.json forensic dump: adds "
                         "the exhaustion-forensics section")
    ap.add_argument("--top", type=int, default=8,
                    help="holders to show per forensic record (default 8)")
    ap.add_argument("--validate", action="store_true",
                    help="fail on schema violations or accounting-"
                         "identity breaks")
    opts = ap.parse_args(argv)
    telemetry = load_module("telemetry")
    accounting = load_accounting()
    try:
        series, errors = read_series(opts.file, telemetry,
                                     validate=opts.validate)
        timeline = read_timeline(opts.file)
    except OSError as e:
        print(f"capacity_report: cannot read {opts.file}: {e}",
              file=sys.stderr)
        return 2
    doc = None
    if opts.forensics:
        try:
            with open(opts.forensics, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"capacity_report: cannot read {opts.forensics}: {e}",
                  file=sys.stderr)
            return 2

    out = [f"Capacity report: {opts.file}", ""]
    out.extend(render_timeline(timeline))
    out.append("")
    out.extend(render_tenants(series))
    out.append("")
    out.extend(render_pool_state(series))
    twins = render_twins(series)
    if twins:
        out.append("")
        out.extend(twins)
    if doc is not None:
        out.append("")
        out.extend(render_forensics(doc, opts.top))
    print("\n".join(out))

    if opts.validate:
        if not series:
            errors.append("file contains no telemetry records")
        errors.extend(validate_identity(opts.file, telemetry))
        if doc is not None:
            try:
                accounting.validate_forensic_doc(doc)
            except ValueError as e:
                errors.append(f"forensics: {e}")
        if errors:
            print("VALIDATION FAILED:", file=sys.stderr)
            for e in errors:
                print(f"  {e}", file=sys.stderr)
            return 1
        n_rec = len((doc or {}).get("records", []))
        print(f"schema OK: {len(series)} series"
              + (f", {n_rec} forensic record(s)" if doc is not None
                 else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
