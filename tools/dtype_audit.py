"""Offline f32-surface audit of a compiled train step (VERDICT r4 ask#1:
the ResNet step is HBM-bound and `convert_reduce_fusion` burns 20.5 ms —
find every activation-sized f32 tensor the traced program materializes,
BEFORE burning a tunnel window measuring).

Dtypes are backend-independent at the StableHLO level, so this runs on
CPU with a small batch (the dtype pattern does not depend on batch) and
reports:
  - every f32 tensor type above a per-image element threshold, with the
    op kinds that produce it (activation-sized f32 = 2x the bytes of the
    bf16 tensor it shadows);
  - the convert-op census (bf16->f32 / f32->bf16) by operand size class.

Usage:
    env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu PYTHONPATH=. \
        python tools/dtype_audit.py [--model resnet|bert|lstm|ssd] [--batch 8]
"""
from __future__ import annotations

import argparse
import collections
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def log(msg):
    print(f"[dtype_audit {time.strftime('%H:%M:%S')}] {msg}",
          file=sys.stderr, flush=True)


_TENSOR = re.compile(r"tensor<([0-9x]+)x(f32|bf16|f16|i32|i8|ui8|i1)>")


def _elems(dims):
    n = 1
    for d in dims.split("x"):
        n *= int(d)
    return n


def audit_text(text, batch, per_img_threshold=16384):
    """Scan StableHLO text: per-line tensor types + op name.  Returns
    (big_f32, converts) where big_f32 maps shape->set(op kinds) for f32
    results above threshold*batch elements."""
    thresh = per_img_threshold * batch
    big_f32 = collections.defaultdict(collections.Counter)
    converts = collections.Counter()
    for line in text.splitlines():
        line = line.strip()
        m_op = re.match(r'%?[\w.#]+ = "?([\w.]+)"?', line)
        op = m_op.group(1) if m_op else "?"
        tensors = _TENSOR.findall(line)
        if not tensors:
            continue
        if "convert" in op:
            # operand -> result dtype transition, bucketed by size
            if len(tensors) >= 2:
                src, dst = tensors[0][1], tensors[-1][1]
                size = "big" if _elems(tensors[0][0]) >= thresh else "small"
                converts[f"{src}->{dst} ({size})"] += 1
            continue
        # result type is the LAST tensor on an assignment line
        dims, dt = tensors[-1]
        if dt == "f32" and _elems(dims) >= thresh:
            big_f32[dims][op] += 1
    return big_f32, converts


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet",
                    choices=["resnet", "bert", "lstm", "ssd"])
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--per-img-threshold", type=int, default=16384,
                    help="f32 tensors above this many elements PER BATCH "
                         "ROW are reported (16384 = 128x128, well below "
                         "any conv activation)")
    args = ap.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")
    import hlo_inspect
    import mfu_probe

    log(f"building {args.model} batch={args.batch} (CPU, trace-only)...")
    builders = {"resnet": hlo_inspect.build_resnet_step,
                "bert": hlo_inspect.build_bert_step,
                "lstm": hlo_inspect.build_lstm_step,
                "ssd": hlo_inspect.build_ssd_step}
    step, batch_args = builders[args.model](False, args.batch)
    log("lowering...")
    import jax.numpy as jnp
    from tpu_mx import random as _random
    raw = tuple(b._data if b is not None and hasattr(b, "_data") else b
                for b in batch_args)
    if step._jitted is None:
        step._build(len(raw))
        step.place()
    key = _random.take_key()
    gacc = step._gacc if step._accum > 1 else {}
    lowered = step._jitted.lower(
        step.values, step.masters, step.opt_states, step._efs, gacc,
        jnp.asarray(1.0, jnp.float32), jnp.asarray(0.1, jnp.float32),
        key, *raw)
    text = lowered.as_text()
    log(f"stablehlo: {len(text.splitlines())} lines")
    big_f32, converts = audit_text(text, args.batch,
                                   args.per_img_threshold)
    print(f"== activation-sized f32 results (>= "
          f"{args.per_img_threshold} elems/batch-row) ==")
    rows = sorted(big_f32.items(), key=lambda kv: -_elems(kv[0]))
    if not rows:
        print("  (none — every large tensor is bf16/int)")
    total = 0
    for dims, ops in rows:
        n = _elems(dims)
        total += n * sum(ops.values())
        print(f"  f32[{dims}] ({n / 1e6:.1f}M elems): "
              + ", ".join(f"{k}x{v}" for k, v in ops.most_common()))
    print(f"  TOTAL large-f32 result elements: {total / 1e6:.1f}M "
          f"(x4 bytes if materialized)")
    print("== convert census ==")
    for k, v in converts.most_common():
        print(f"  {k}: {v}")


if __name__ == "__main__":
    main()
