"""Training-log parser (REF:tools/parse_log.py — the reference turned
`Module.fit`/Speedometer console logs into per-epoch accuracy/time tables;
same job here for the tpu_mx log format, which mirrors the reference's).

    python tools/parse_log.py train.log                 # markdown table
    python tools/parse_log.py train.log --format csv
    python tools/parse_log.py train.log --format json   # machine-readable

Recognized lines (produced by callback.Speedometer and Module.fit /
model-zoo example loops):
    Epoch[3] Batch [40]  Speed: 1234.56 samples/sec  accuracy=0.912
    Epoch[3] Train-accuracy=0.931
    Epoch[3] Validation-accuracy=0.907
    Epoch[3] Time cost=12.345
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from collections import defaultdict

SPEED_RE = re.compile(
    r"Epoch\[(\d+)\]\s+Batch\s*\[(\d+)\]\s+Speed:\s*([\d.]+)\s*samples/sec")
TRAIN_RE = re.compile(r"Epoch\[(\d+)\]\s+Train-([\w.]+)=([-\d.eE]+)")
VAL_RE = re.compile(r"Epoch\[(\d+)\]\s+Validation-([\w.]+)=([-\d.eE]+)")
TIME_RE = re.compile(r"Epoch\[(\d+)\]\s+Time cost=([\d.]+)")


def parse(lines):
    """Returns a list of per-epoch dicts, epoch-ordered."""
    speeds = defaultdict(list)
    epochs = defaultdict(dict)
    for line in lines:
        m = SPEED_RE.search(line)
        if m:
            speeds[int(m.group(1))].append(float(m.group(3)))
            continue
        m = TRAIN_RE.search(line)
        if m:
            epochs[int(m.group(1))][f"train-{m.group(2)}"] = \
                float(m.group(3))
            continue
        m = VAL_RE.search(line)
        if m:
            epochs[int(m.group(1))][f"val-{m.group(2)}"] = float(m.group(3))
            continue
        m = TIME_RE.search(line)
        if m:
            epochs[int(m.group(1))]["time_s"] = float(m.group(2))
    for e, ss in speeds.items():
        epochs[e]["speed_mean"] = round(sum(ss) / len(ss), 2)
    return [dict(epoch=e, **epochs[e]) for e in sorted(epochs)]


def render(rows, fmt):
    if fmt == "json":
        return json.dumps(rows, indent=1)
    cols = ["epoch"]
    for r in rows:
        for k in r:
            if k not in cols:
                cols.append(k)
    if fmt == "csv":
        out = [",".join(cols)]
        out += [",".join(str(r.get(c, "")) for c in cols) for r in rows]
        return "\n".join(out)
    # markdown
    out = ["| " + " | ".join(cols) + " |",
           "|" + "|".join("---" for _ in cols) + "|"]
    out += ["| " + " | ".join(str(r.get(c, "")) for c in cols) + " |"
            for r in rows]
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("logfile", nargs="+")
    ap.add_argument("--format", choices=("markdown", "csv", "json"),
                    default="markdown")
    args = ap.parse_args(argv)
    lines = []
    for path in args.logfile:
        with open(path) as f:
            lines.extend(f)
    rows = parse(lines)
    if not rows:
        print("no recognized log lines found", file=sys.stderr)
        return 1
    print(render(rows, args.format))
    return 0


if __name__ == "__main__":
    sys.exit(main())
