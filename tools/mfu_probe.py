"""MFU / roofline probe on the real chip (VERDICT r3 ask#3 + weak#7).

For each workload config this measures the full compiled train step and
records, side by side:
  - measured throughput + MFU from the analytic FLOPs model (bench.py's),
  - XLA's OWN cost-analysis FLOPs and the MFU implied by them — the
    cross-check VERDICT weak#7 asked for (the analytic model is
    hand-maintained; if the two disagree badly the model is wrong),
  - layout/copy smell counts from the compiled HLO (transpose/pad/copy),
  - the compiled memory analysis (are we near the 16 GB HBM ceiling?).

Every config's record is persisted to MFU_PROBE_<round>.json as soon as it
exists (the bench lastgood lesson — a mid-run tunnel wedge keeps earlier
rows).  Run by tools/tpu_watch.py after the bench, or by hand:
    python tools/mfu_probe.py [--out PATH] [--configs resnet:512,...]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

V5E_PEAK_FLOPS = 197e12

# the default probe sweep; tools/tpu_watch.py imports this so its
# done-predicate can never drift from what the probe actually produces
# (a hand-maintained copy once listed a key the probe never emitted,
# and the watcher re-ran the probe every backoff cycle).
# BECAUSE of that import, this module's TOP LEVEL must stay stdlib-only:
# hoisting `import jax` here would make the watcher (whose design
# contract is "imports NO jax — a wedged backend hangs the importing
# process in a C call") hang at startup exactly when the tunnel is down.
DEFAULT_CONFIGS = ("resnet:256", "resnet:512", "bert:512", "bert:256",
                   "bert_flash:512")


def log(msg):
    print(f"[mfu {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def _is_oom(e):
    s = f"{type(e).__name__}: {e}".lower()
    return ("ran out of memory" in s or "out of memory" in s
            or "resource_exhausted" in s or "exceeded hbm capacity" in s)


def _compile_step(step, batch_args):
    # the AOT lower+compile path lives on CompiledTrainStep itself now
    # (bench.py's XLA-cost MFU shares it)
    return step.aot_compiled(*batch_args)


def _timed_steps(step, batch_args, warmup, iters):
    import numpy as np
    fetch = lambda l: float(np.asarray(l._data).ravel()[0])
    loss = step.step(*batch_args)
    fetch(loss)
    for _ in range(warmup):
        fetch(step.step(*batch_args))
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step.step(*batch_args)
    fetch(loss)
    return (time.perf_counter() - t0) / iters


def probe_one(model, batch):
    import contextlib

    attn_override = None
    if model == "bert_dense":
        # A/B the attention path: at T=128 the single-block flash kernel
        # vs XLA's fused dense attention is an empirical question.  The
        # env knob is read at TRACE time, so it must span compile+timing.
        # Since auto now RESOLVES to dense at short T (the measured r4
        # winner), the flash arm needs an explicit pin — the plain 'bert'
        # config measures what production auto picks.
        model, attn_override = "bert", "dense"
    elif model == "bert_flash":
        model, attn_override = "bert", "flash"
    with contextlib.ExitStack() as stack:
        if attn_override:
            prior = os.environ.get("TPUMX_ATTENTION")
            os.environ["TPUMX_ATTENTION"] = attn_override

            def restore():
                if prior is None:
                    os.environ.pop("TPUMX_ATTENTION", None)
                else:
                    os.environ["TPUMX_ATTENTION"] = prior

            stack.callback(restore)
        return _probe_one(model, batch)


def _probe_one(model, batch):
    import hlo_inspect
    import bench as bench_mod

    # record what the trace will actually read, not what the caller
    # thinks it set — a user-level TPUMX_ATTENTION pin applies to every
    # rung and must show up in the artifact
    attn_mode = os.environ.get("TPUMX_ATTENTION", "auto")
    log(f"building {model} batch={batch} (attention={attn_mode})...")
    if model == "resnet":
        step, batch_args = hlo_inspect.build_resnet_step(False, batch)
        unit_flops = bench_mod.RESNET50_TRAIN_FLOPS_PER_IMG
    else:
        step, batch_args = hlo_inspect.build_bert_step(False, batch)
        seq_len, n_masked = 128, max(1, int(0.15 * 128))
        unit_flops = bench_mod.bert_train_flops_per_seq(
            12, 768, 3072, 30522, seq_len, n_masked)

    log("compiling...")
    compiled = _compile_step(step, batch_args)
    txt = compiled.as_text()
    ops, convs, fusions = hlo_inspect.analyze(txt)
    smells = {k: ops.get(k, 0) for k in
              ("transpose", "copy", "pad", "reshape", "convert")}
    xla_flops = None
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        xla_flops = float(ca.get("flops", 0.0)) or None
    except Exception as e:
        log(f"cost_analysis unavailable: {e}")
    mem = {}
    try:
        ma = compiled.memory_analysis()
        for k in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(ma, k, None)
            if v is not None:
                mem[k] = int(v)
    except Exception:
        pass

    log("timing...")
    sec = _timed_steps(step, batch_args, warmup=3, iters=15)
    per_sec = batch / sec
    rec = {
        "model": model, "batch": batch,
        "attention": attn_mode,
        "step_seconds": round(sec, 5),
        "throughput_per_sec": round(per_sec, 2),
        "mfu_analytic_model": round(per_sec * unit_flops / V5E_PEAK_FLOPS,
                                    4),
        "hlo": {"fusions": fusions, "smells": smells,
                "n_convolutions": len(convs)},
        "memory": mem,
    }
    if xla_flops:
        # cost_analysis flops are per program execution (the whole batch)
        rec["xla_cost_flops_per_step"] = xla_flops
        rec["mfu_xla_cost"] = round(xla_flops / sec / V5E_PEAK_FLOPS, 4)
        rec["analytic_vs_xla_flops_ratio"] = round(
            (unit_flops * batch) / xla_flops, 4)
    # ONE number of record (VERDICT r4 ask#9): mfu = the XLA-cost value
    # when the backend exposes cost_analysis, analytic model otherwise;
    # both raw fields stay for the cross-check
    rec["mfu"] = rec.get("mfu_xla_cost", rec["mfu_analytic_model"])
    rec["mfu_source"] = ("xla_cost_analysis" if xla_flops
                         else "analytic_model")
    return rec


def main():
    ap = argparse.ArgumentParser()
    from artifact_protocol import artifact
    ap.add_argument("--out", default=artifact("MFU_PROBE"))
    ap.add_argument("--configs", default=",".join(DEFAULT_CONFIGS))
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU (harness smoke; mirrors conftest)")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    else:
        from tpu_mx.runtime import enable_shared_compilation_cache
        enable_shared_compilation_cache()
    platform = jax.devices()[0].platform
    from artifact_protocol import (load_prior, merge_prior_sections,
                                   refuses_clobber, write_atomic)
    record = {"ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
              "platform": platform, "peak_flops": V5E_PEAK_FLOPS,
              "configs": {}}
    prior = load_prior(args.out)
    if refuses_clobber(prior, platform):
        log(f"platform is {platform}, not tpu; refusing to overwrite "
            f"the hardware artifact {args.out} (pass --out elsewhere "
            "for a smoke run)")
        return 1
    # a partial run (--configs retry after one transport blip) must MERGE
    # into the existing artifact, not clobber the other rows: keep prior
    # same-platform rows for configs this run does not touch (this run's
    # result, including a recorded error, still replaces its own row)
    if not args.cpu:
        merge_prior_sections(record, prior, ("configs",),
                             require_platform=platform)
    if platform != "tpu" and not args.cpu:
        record["skipped"] = True
        record["reason"] = f"platform is {platform}, not tpu"
        log(record["reason"])
        probed = []
    else:
        record["skipped"] = False
        probed = []  # keys THIS run attempts (exit code ignores merged rows)
        seen_ok = set()
        for item in args.configs.split(","):
            model, b = item.strip().split(":")
            batch = int(b)
            if args.cpu:  # smoke shapes: prove the harness, not the chip
                batch = min(batch, 8)
            if model in seen_ok and args.cpu:
                continue
            probed.append(f"{model}:{batch}")
            t0 = time.perf_counter()
            try:
                rec = probe_one(model, batch)
                record["configs"][f"{model}:{batch}"] = rec
                seen_ok.add(model)
                log(f"{model}:{batch} -> {rec['throughput_per_sec']}/s "
                    f"mfu={rec['mfu_analytic_model']}")
            except Exception as e:
                err = f"{type(e).__name__}: {e}"[:400]
                record["configs"][f"{model}:{batch}"] = {
                    "model": model, "batch": batch, "error": err,
                    "oom": _is_oom(e),
                    "seconds": round(time.perf_counter() - t0, 1)}
                log(f"{model}:{batch} FAILED {err}")
            write_atomic(args.out, record)
    write_atomic(args.out, record)
    ok = (not record["skipped"] and probed and
          any("error" not in record["configs"][k] for k in probed))
    log(f"done: {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
