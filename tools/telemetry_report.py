#!/usr/bin/env python
"""Render a TPUMX_TELEMETRY JSONL file as a human-readable report.

Histogram series are rendered in the same per-scope aggregate table format
``mx.profiler.dumps()`` uses (Name / Calls / Total / Mean / Min / Max, in
ms), followed by counter and gauge sections.  Because each flush appends a
CUMULATIVE snapshot, the report aggregates by taking the LAST record of
every (name, labels) series.

Modes (the ``obs`` tier of tools/ci.py runs the first two):

    python tools/telemetry_report.py metrics.jsonl
    python tools/telemetry_report.py metrics.jsonl --validate \
        --require fusion.flushes,checkpoint.save_seconds
    python tools/telemetry_report.py --diff A.jsonl B.jsonl
    python tools/telemetry_report.py --merge ctl.jsonl obs/rank-*.jsonl

``--merge`` renders the FLEET view over N per-rank snapshot files using
the cross-worker merge core (tpu_mx/parallel/fleet_obs.py): counters
sum, histograms bucket-merge, gauges spread to min/mean/max — the same
code path the supervising launcher aggregates with, so the offline view
and the live rollup can never disagree.  ``--validate`` additionally
re-proves the aggregation identity (every merged counter equals its
per-rank sum) and ``--require`` gates the merged view (the ``fleet_obs``
preset spans worker + controller registries).

``--diff`` renders the DELTA between two snapshots (soak runs, bench
A/Bs): counter values and histogram count/sum are subtracted (B - A),
gauges — last-written values, not accumulators — are shown side by side.
Series present in only one file are marked ``(only in A/B)``.
``--require`` composes: the gate applies to B, the "after" snapshot.

``--validate`` checks every record against the telemetry schema
(name/type/value/ts present; histogram bucket monotonicity) and fails on
metric names outside ``telemetry.KNOWN_METRICS`` — stable metric names are
an API, and this is the gate that catches accidental renames.
``--require`` additionally fails unless each listed metric exists with a
nonzero value (counter > 0 / histogram count > 0 / gauge != 0).  A token
naming a preset (``supervisor`` — the self-healing recovery counters the
``soak`` CI tier gates on) expands to its metric list.

The telemetry module is loaded standalone from its file — this tool never
imports the ``tpu_mx`` package (which would boot jax) just to read JSON.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys


# --require presets: one token → a metric family.  "supervisor" gates the
# soak tier: every recovery path must have actually fired (the degraded
# gauge is deliberately absent — it is 0 on any healthy run).  "resume"
# gates the deterministic-resume leg: capsules were written AND a restore
# actually went through the capsule path (resume_step_gap is deliberately
# absent — it must be 0 under capsules, asserted in the soak script).
REQUIRE_PRESETS = {
    "supervisor": ("supervisor.restarts", "supervisor.rollbacks",
                   "supervisor.watchdog_fires",
                   "supervisor.batches_skipped"),
    "resume": ("resume.capsules_written",
               "resume.capsule_restore_seconds"),
    # "serve" gates the serve tier: the SLO histograms must have samples,
    # throughput must be nonzero, and the chaos schedule must have
    # actually driven an engine restart (queue_depth/cache_utilization
    # are deliberately absent — both are rightly 0 once a run drains).
    # The SLO-engine additions (ISSUE 11): per-request phase attribution
    # must have landed, and the live monitor must have published its
    # windowed estimate and attainment gauges (burn_rate/breaching are
    # deliberately absent — both are rightly 0 on a healthy run).
    # The recovery additions (ISSUE 19): the storm legs arm the token
    # journal and run on the prefill-replay arm, so restarts must have
    # been paid for with replay prefills and the journal must have
    # actually recorded admissions/tokens/fsyncs (redecode_tokens and
    # replay_fallbacks are deliberately absent — both are rightly 0 on
    # the replay arm with an intact journal).
    "serve": ("serve.requests", "serve.ttft_seconds", "serve.itl_seconds",
              "serve.generated_tokens", "serve.decode_steps",
              "serve.tokens_per_sec", "serve.engine_restarts",
              "serve.phase_seconds", "serve.slo_estimate_seconds",
              "serve.slo_attainment", "serve.replay_requests",
              "serve.replay_tokens", "serve.journal_requests",
              "serve.journal_tokens", "serve.journal_bytes"),
    # "fleet" gates the membership-churn soak leg (ISSUE 17): the epoch
    # gauge must have moved past 0, at least one reshard was driven
    # through the seam, and at least one evicted/late worker was admitted
    # back (lost_workers/worker_restarts are deliberately absent — a
    # planned-scale-only churn run legitimately loses nobody).
    "fleet": ("fleet.membership_epoch", "fleet.reshards", "fleet.rejoins"),
    # "fleet_obs" gates the fleet observability plane (ISSUE 18): workers
    # actually shipped snapshots, the controller's aggregation pass saw
    # them, and at least one step was observed by >= 2 ranks so cross-
    # rank skew exists.  Spans worker AND controller registries — meant
    # for `--merge controller.jsonl <fleet_dir>/obs/rank-*.jsonl`
    # (straggler_signal is deliberately absent: it is rightly 0 on a
    # straggler-free run).
    "fleet_obs": ("fleet.obs_records", "fleet.ranks_reporting",
                  "fleet.step_skew_seconds"),
    # "integrity" gates the SDC-storm soak leg (ISSUE 20): every rank
    # published fingerprints, votes were held, the injected flip was
    # actually seen as a mismatch, and the corrupt rank was quarantined.
    # Spans all ranks' registries — meant for
    # `--merge <fleet_dir>/obs/rank-*.jsonl` (shadow_audits /
    # self_checks are deliberately absent: the vote path needs neither,
    # and a train fleet legitimately runs with both samplers off).
    "integrity": ("integrity.fingerprints", "integrity.votes",
                  "integrity.mismatches", "integrity.quarantined"),
}


def expand_required(spec):
    """Comma-separated metric names / preset tokens → the flat name list."""
    names = []
    for token in spec.split(","):
        if not token:
            continue
        names.extend(REQUIRE_PRESETS.get(token, (token,)))
    return names


def load_telemetry():
    """Load tpu_mx/telemetry.py WITHOUT importing the tpu_mx package
    (telemetry.py is stdlib-only at module level by contract)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "tpu_mx", "telemetry.py")
    spec = importlib.util.spec_from_file_location("_tpumx_telemetry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_fleet_obs():
    """Load the fleet-observability merge core the same standalone way
    (its merge/correlate functions are stdlib-only by contract; the
    package bridges degrade to None on a standalone load)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "tpu_mx", "parallel", "fleet_obs.py")
    spec = importlib.util.spec_from_file_location("_tpumx_fleet_obs", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def read_records(path, telemetry, validate=False):
    """Parse the JSONL file into (records, stamps, errors) — every
    record in file order.  With validate=True, schema violations and
    unknown metric names land in `errors` instead of being silently
    passed through."""
    records = []
    stamps = set()
    errors = []
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                errors.append(f"line {lineno}: not JSON: {e}")
                continue
            if validate:
                try:
                    telemetry.validate_record(rec)
                except ValueError as e:
                    errors.append(f"line {lineno}: {e}")
                    continue
                if rec["name"] not in telemetry.KNOWN_METRICS:
                    errors.append(
                        f"line {lineno}: unknown metric name "
                        f"{rec['name']!r} — not in telemetry.KNOWN_METRICS "
                        "(stable names are an API; register new metrics in "
                        "the catalog + docs/observability.md)")
                    continue
            records.append(rec)
            if "ts" in rec:
                stamps.add(rec["ts"])
    return records, stamps, errors


def read_series(path, telemetry, validate=False):
    """Parse the JSONL file into {(name, labels_json): last_record}.

    Returns (series, n_snapshots, errors).  With validate=True, schema
    violations and unknown metric names land in `errors` instead of being
    silently passed through."""
    records, stamps, errors = read_records(path, telemetry,
                                           validate=validate)
    series = {}
    for rec in records:
        key = (rec.get("name"),
               json.dumps(rec.get("labels", {}), sort_keys=True))
        series[key] = rec
    return series, len(stamps), errors


def _series_label(name, labels_json):
    labels = json.loads(labels_json)
    if not labels:
        return name
    body = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{body}}}"


def render(series, n_snapshots, path):
    """The report string: histogram table (profiler.dumps format) +
    counter/gauge sections."""
    hists = {k: r for k, r in series.items() if r["type"] == "histogram"}
    counters = {k: r for k, r in series.items() if r["type"] == "counter"}
    gauges = {k: r for k, r in series.items() if r["type"] == "gauge"}
    lines = [f"Telemetry report: {path}",
             f"  {n_snapshots} snapshot(s), {len(series)} series", ""]

    def table(entries, scale, suffix):
        lines.append("%-40s %8s %12s %12s %12s %12s" %
                     ("Name", "Calls", f"Total{suffix}", f"Mean{suffix}",
                      f"Min{suffix}", f"Max{suffix}"))
        for (name, lj), rec in entries:
            n = rec["value"]
            tot = rec.get("sum", 0.0)
            if n:
                lines.append("%-40s %8d %12.3f %12.3f %12.3f %12.3f" % (
                    _series_label(name, lj), n, tot * scale,
                    tot / n * scale, rec.get("min", 0.0) * scale,
                    rec.get("max", 0.0) * scale))
            else:
                lines.append("%-40s %8d %12.3f %12s %12s %12s" % (
                    _series_label(name, lj), 0, 0.0, "-", "-", "-"))
        lines.append("")

    # seconds-unit histograms render in the profiler.dumps() ms table;
    # count-valued ones (e.g. fusion.segment_ops) keep their own unit
    timed = sorted((k, r) for k, r in hists.items()
                   if r.get("unit", "seconds") == "seconds")
    other = sorted((k, r) for k, r in hists.items()
                   if r.get("unit", "seconds") != "seconds")
    if timed:
        table(timed, 1e3, "(ms)")
    for (name, lj), rec in other:
        table([((name, lj), rec)], 1.0, f"({rec.get('unit', '')})")
    if counters:
        lines.append("Counters:")
        for (name, lj), rec in sorted(counters.items()):
            lines.append("  %-50s %s" % (_series_label(name, lj),
                                         rec["value"]))
        lines.append("")
    if gauges:
        lines.append("Gauges:")
        for (name, lj), rec in sorted(gauges.items()):
            lines.append("  %-50s %g" % (_series_label(name, lj),
                                         rec["value"]))
        lines.append("")
    return "\n".join(lines)


def render_diff(series_a, series_b, path_a, path_b):
    """The delta view: counters/histograms subtracted (B - A), gauges
    side-by-side — what a soak-vs-soak or bench A/B comparison needs
    without hand-parsing two JSONL files."""
    name_a = os.path.basename(path_a)
    name_b = os.path.basename(path_b)
    lines = [f"Telemetry diff: A={path_a}  B={path_b}",
             f"  {len(series_a)} series in A, {len(series_b)} in B", ""]
    keys = sorted(set(series_a) | set(series_b))

    def sided(key):
        a, b = series_a.get(key), series_b.get(key)
        if a is None:
            return b, "(only in B)"
        if b is None:
            return a, "(only in A)"
        return None, None

    rows_c, rows_h, rows_g = [], [], []
    for key in keys:
        label = _series_label(*key)
        rec, only = sided(key)
        kind = (rec or series_b.get(key) or series_a.get(key))["type"]
        if only is not None:
            val = rec["value"]
            if kind == "histogram":
                rows_h.append("  %-50s %s count=%s sum=%.6g"
                              % (label, only, val, rec.get("sum", 0.0)))
            elif kind == "counter":
                rows_c.append("  %-50s %s value=%s" % (label, only, val))
            else:
                rows_g.append("  %-50s %s value=%g" % (label, only, val))
            continue
        a, b = series_a[key], series_b[key]
        if kind == "counter":
            rows_c.append("  %-50s %+d   (A=%d, B=%d)"
                          % (label, b["value"] - a["value"],
                             a["value"], b["value"]))
        elif kind == "histogram":
            dc = b["value"] - a["value"]
            ds = b.get("sum", 0.0) - a.get("sum", 0.0)
            mean = (ds / dc) if dc else 0.0
            rows_h.append("  %-50s count %+d  sum %+.6g  mean %.6g"
                          % (label, dc, ds, mean))
        else:
            rows_g.append("  %-50s A=%-12g B=%-12g"
                          % (label, a["value"], b["value"]))
    if rows_c:
        lines += [f"Counters (B - A; A={name_a}, B={name_b}):",
                  *rows_c, ""]
    if rows_h:
        lines += ["Histograms (count/sum deltas, mean of the delta):",
                  *rows_h, ""]
    if rows_g:
        lines += ["Gauges (side by side — last-written values, "
                  "not accumulators):", *rows_g, ""]
    return "\n".join(lines)


def check_required(series, required):
    """Names in `required` must exist with a nonzero value; returns the
    list of violation strings (empty = good)."""
    problems = []
    by_name = {}
    for (name, _lj), rec in series.items():
        prev = by_name.get(name)
        if prev is None or rec["value"] > prev["value"]:
            by_name[name] = rec
    for name in required:
        rec = by_name.get(name)
        if rec is None:
            problems.append(f"required metric {name!r} never emitted")
        elif not rec["value"]:
            kind = rec["type"]
            what = "count" if kind == "histogram" else "value"
            problems.append(f"required metric {name!r} has zero {what}")
    return problems


def run_merge(opts, telemetry, ap):
    """--merge: fold N per-rank JSONL files through the fleet merge core
    (tpu_mx/parallel/fleet_obs.py — counters sum, histograms bucket-
    merge, gauges spread) and render/gate the FLEET view.  Each file's
    rank comes from its records' ``rank`` stamp; unstamped files (a
    controller's own registry) get distinct negative pseudo-ranks so
    they can ride along without colliding with a real rank."""
    if len(opts.file) < 2:
        ap.error("--merge needs at least two files: a.jsonl b.jsonl ...")
    fleet_obs = load_fleet_obs()
    streams = {}
    errors = []
    for idx, path in enumerate(opts.file):
        recs, _stamps, errs = read_records(path, telemetry,
                                           validate=opts.validate)
        errors += [f"{os.path.basename(path)}: {e}" for e in errs]
        rank = next((r["rank"] for r in recs
                     if isinstance(r.get("rank"), int)
                     and not isinstance(r.get("rank"), bool)), -1 - idx)
        streams.setdefault(rank, []).extend(recs)
    try:
        merged, info = fleet_obs.merge_streams(streams)
    except ValueError as e:
        print(f"VALIDATION FAILED:\n  merge: {e}", file=sys.stderr)
        return 1
    series = {(r["name"],
               json.dumps(r.get("labels", {}), sort_keys=True)): r
              for r in merged}
    print(render(series, len(opts.file),
                 " + ".join(os.path.basename(p) for p in opts.file)))
    print(f"Merged {len(opts.file)} file(s) as rank(s) "
          f"{info['ranks']} ({info['records_read']} record(s) read; "
          "negative ranks are unstamped files)")
    if opts.validate:
        # the aggregation exactness invariant, re-checked on the way out
        for rec in merged:
            if rec["type"] == "counter":
                total = sum(rec["per_rank"].values())
                if total != rec["value"]:
                    errors.append(
                        f"aggregation identity violated: {rec['name']} "
                        f"merged value {rec['value']} != per-rank sum "
                        f"{total}")
    errors += check_required(series, expand_required(opts.require))
    if not series and not errors:
        errors.append("no file contains telemetry records")
    if errors:
        print("VALIDATION FAILED:", file=sys.stderr)
        for e in errors:
            print(f"  {e}", file=sys.stderr)
        return 1
    if opts.validate:
        print(f"schema OK: {len(series)} merged series from "
              f"{len(info['ranks'])} rank(s); aggregation identity holds")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("file", nargs="+",
                    help="TPUMX_TELEMETRY JSONL file (two with --diff, "
                         "two or more with --merge)")
    ap.add_argument("--validate", action="store_true",
                    help="fail on schema violations or unknown metric names")
    ap.add_argument("--require", default="",
                    help="comma-separated metric names (or preset tokens: "
                         f"{', '.join(REQUIRE_PRESETS)}) that must be "
                         "present and nonzero")
    ap.add_argument("--diff", action="store_true",
                    help="delta view between exactly two snapshot files "
                         "(counters/histograms subtracted, gauges side "
                         "by side)")
    ap.add_argument("--merge", action="store_true",
                    help="fleet view over N per-rank snapshot files "
                         "(counters summed, histograms bucket-merged, "
                         "gauges spread — the fleet_obs merge core); "
                         "--validate/--require apply to the merged view")
    opts = ap.parse_args(argv)
    telemetry = load_telemetry()
    if opts.merge:
        if opts.diff:
            ap.error("--merge and --diff are mutually exclusive")
        return run_merge(opts, telemetry, ap)
    if opts.diff:
        if len(opts.file) != 2:
            ap.error("--diff needs exactly two files: A.jsonl B.jsonl")
        path_a, path_b = opts.file
        series_a, _, errors_a = read_series(path_a, telemetry,
                                            validate=opts.validate)
        series_b, _, errors_b = read_series(path_b, telemetry,
                                            validate=opts.validate)
        print(render_diff(series_a, series_b, path_a, path_b))
        errors = [f"A: {e}" for e in errors_a] + \
                 [f"B: {e}" for e in errors_b]
        # --require composes with --diff: the gate applies to B (the
        # "after" snapshot) — silently ignoring it would let a soak
        # comparison read green with its requirement never evaluated
        errors += [f"B: {e}" for e in
                   check_required(series_b,
                                  expand_required(opts.require))]
        if not (series_a or series_b) and not errors:
            errors.append("neither file contains telemetry records")
        if errors:
            print("VALIDATION FAILED:", file=sys.stderr)
            for e in errors:
                print(f"  {e}", file=sys.stderr)
            return 1
        return 0
    if len(opts.file) != 1:
        ap.error("exactly one file expected (use --diff to compare two)")
    series, n_snapshots, errors = read_series(opts.file[0], telemetry,
                                              validate=opts.validate)
    print(render(series, n_snapshots, opts.file[0]))
    required = expand_required(opts.require)
    errors += check_required(series, required)
    if not series and not errors:
        errors.append("file contains no telemetry records")
    if errors:
        print("VALIDATION FAILED:", file=sys.stderr)
        for e in errors:
            print(f"  {e}", file=sys.stderr)
        return 1
    if opts.validate:
        print(f"schema OK: {len(series)} series, all names in the catalog")
    return 0


if __name__ == "__main__":
    sys.exit(main())
