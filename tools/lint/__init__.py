"""tpumx-lint: framework-aware static analysis for the tpu-mx contracts.

PR 6 shipped the linter as five independent per-file AST walks in one
module; ISSUE 10 grew it into a two-phase analyzer and split it into
this package:

- ``lint.core``   — findings, the per-file context, suppressions,
  baseline I/O, static catalog extraction;
- ``lint.index``  — phase 1: the project-wide symbol table, call graph,
  per-function summaries, lock-context propagation, hot-path
  reachability, and the serialized index cache;
- ``lint.passes`` — phase 2: the rule passes (durability, determinism,
  sync-point, concurrency, telemetry-catalog, hot-path-purity);
- ``lint.cli``    — the driver (``lint_source``/``lint_sources``/
  ``lint_paths``/``main``), including ``--changed-only``.

``tools/tpumx_lint.py`` remains the entry point and the public import
surface (tests and CI use it); it re-exports everything below, so
``import tpumx_lint`` keeps working unchanged.  See
docs/static_analysis.md.
"""
from .core import (DEFAULT_TARGETS, LINT_FORMAT, REPO, FileCtx, Finding,
                   call_name, const_str, dotted, expr_text,
                   load_known_events, load_known_metrics, read_baseline,
                   strings_in, suppressed_rules, write_baseline)
from .index import (HOT_ROOTS, INDEX_FORMAT, ProjectIndex, build_index,
                    read_index, summarize_file, write_index)
from .passes import (ConcurrencyPass, DeterminismPass, DurabilityPass,
                     HotPathPurityPass, Pass, SyncPointPass,
                     TelemetryCatalogPass, build_passes)
from .cli import (DEFAULT_INDEX, git_changed_files, iter_files,
                  lint_paths, lint_source, lint_sources, main)

__all__ = [
    "DEFAULT_INDEX", "DEFAULT_TARGETS", "HOT_ROOTS", "INDEX_FORMAT",
    "LINT_FORMAT", "REPO", "FileCtx", "Finding", "ProjectIndex",
    "ConcurrencyPass", "DeterminismPass", "DurabilityPass",
    "HotPathPurityPass", "Pass", "SyncPointPass", "TelemetryCatalogPass",
    "build_index", "build_passes", "call_name", "const_str", "dotted",
    "expr_text", "git_changed_files", "iter_files", "lint_paths",
    "lint_source", "lint_sources", "load_known_events",
    "load_known_metrics", "main", "read_baseline", "read_index",
    "strings_in", "summarize_file", "suppressed_rules", "write_baseline",
    "write_index",
]
