"""tpumx-lint phase 1: the project-wide index.

One pass over every scanned file builds, per function, a *summary* —
calls made (and whether each call site sits under a lock), implicit
device→host syncs, raw parameter-path writes, jit-boundary and
memoization markers — plus per-file symbol tables (functions, classes,
``self.attr`` constructor types, import aliases).  ``link()`` then
resolves call sites into a project call graph and derives the facts the
interprocedural passes (``tools/lint/passes.py``) consume:

- **lock context propagation** — ``always_locked(fn)`` is a greatest
  fixpoint over the call graph: a function is proven to run under a lock
  when every project call site either sits lexically inside a
  ``with <lock>:`` or belongs to a function that is itself always
  locked.  Cycles are resolved optimistically (a recursive helper whose
  only external entries are locked is locked).  Zero callers → not
  provable, the lexical finding stands.
- **hot-path reachability** — BFS from the decode/train/fusion hot-path
  roots (``HOT_ROOTS``); every reached function carries one example call
  chain for the finding message.
- **one-hop helper summaries** — the sync-point and durability passes
  look up a callee's summary at the call site (a wrapper around
  ``open(path, "w")`` or a helper hiding an ``.item()`` is no longer a
  blind spot).
- **emitter alias closure** — names that resolve, transitively through
  re-exporting modules, to ``tpu_mx.telemetry`` / ``tpu_mx.tracing`` or
  their emitter functions, so the catalog pass checks aliased
  cross-module call sites.

Call resolution is deliberately lightweight (this is a linter, not a
compiler): ``self.m()`` → same-class method; ``self.attr.m()`` via
``self.attr = ClassName(...)`` constructor assignments; bare names via
lexical nesting, module scope, then (re-exported) imports; dotted names
through import aliases and submodules.  As a last resort a method name
defined by **exactly one** project class resolves to it (the
unique-method heuristic), except for generic names (``COMMON_METHODS``)
where a wrong edge would be likely.  Unresolved calls simply contribute
no edge — the analysis under-approximates, which for lock *proofs* is
the safe direction (an unproven helper keeps its finding) and for
reachability trades recall for a zero-false-positive default.

The index serializes to JSON next to the baseline
(``tools/tpumx_lint_index.json``, sha-keyed per file) so
``--changed-only`` re-summarizes only dirty files and re-analyzes just
the dirty call-graph region (the changed files' strongly-connected
components plus their direct callers/callees).
"""
from __future__ import annotations

import ast
import hashlib
import json
import re

from .core import (SYNC_ATTRS, SYNC_REDUCTIONS, FileCtx, call_name, dotted,
                   expr_text, flat_targets, jnp_names, numpy_names,
                   strings_in, suppressed_rules)

INDEX_FORMAT = "tpumx-lint-index-v1"

# The hot-path roots: the per-token / per-step loops whose transitive
# callees must stay pure (no eager host↔device traffic) — the
# hot-path-purity pass (docs/static_analysis.md, docs/performance.md).
HOT_ROOTS = (
    ("tpu_mx/serving/engine.py", "EngineCore.decode"),
    ("tpu_mx/serving/attention.py", "decode_attention"),
    # the fused whole-step decode program (ISSUE 16): the step body
    # itself is jitted, but the dispatch wrapper runs per decode step —
    # an eager conversion creeping into it would silently reintroduce
    # the per-step host traffic the fused arm exists to remove
    ("tpu_mx/serving/jax_model.py", "JaxTinyLM.decode_step"),
    ("tpu_mx/parallel/train_step.py", "CompiledTrainStep.step"),
    ("tpu_mx/parallel/train_step.py", "CompiledTrainStep._step"),
    ("tpu_mx/fusion.py", "flush"),
    ("tpu_mx/fusion.py", "realize"),
)

# method names too generic for the unique-method fallback: an edge from
# `fh.write(...)` to some class's `write` would poison the call graph
COMMON_METHODS = frozenset({
    "write", "read", "get", "set", "pop", "append", "extend", "update",
    "close", "open", "run", "start", "stop", "join", "items", "keys",
    "values", "copy", "add", "clear", "flush", "emit", "put", "send",
    "next", "reset", "step", "save", "load", "free", "alloc",
})

_MEMO_TEST_RE = re.compile(r"is (not )?None\b")


def module_of(relpath):
    """'tpu_mx/serving/engine.py' -> ('tpu_mx.serving.engine', is_pkg)."""
    parts = relpath[:-3].split("/") if relpath.endswith(".py") \
        else relpath.split("/")
    if parts and parts[-1] == "__init__":
        return ".".join(parts[:-1]), True
    return ".".join(parts), False


def _is_lock_with(item):
    d = dotted(item.context_expr) or ""
    return bool(d) and "lock" in d.lower()


def _decorator_names(node):
    out = []
    for dec in node.decorator_list:
        d = dotted(dec)
        if d is None and isinstance(dec, ast.Call):
            d = dotted(dec.func)
            if d in ("functools.partial", "partial") and dec.args:
                inner = dotted(dec.args[0])
                if inner:
                    out.append(inner)
        if d:
            out.append(d)
    return out


def _param_names(fn):
    a = fn.args
    names = {p.arg for p in (a.args + a.kwonlyargs
                             + getattr(a, "posonlyargs", []))}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names


def summarize_file(ctx):
    """Phase-1 summary of one parsed file: plain-data (JSON-able) dict."""
    np_aliases = numpy_names(ctx)
    jnp_aliases = jnp_names(ctx)
    funcs = {}       # qualname -> summary dict
    classes = {}     # class qualname -> {"methods": [...], "attr_types": {}}
    jit_names = set()  # function NAMES referenced inside jax.jit/pallas_call

    # -- collect jit-referenced names (file-wide) ---------------------------
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        d = call_name(node) or ""
        base = d.split(".")[-1]
        if base in ("jit", "pjit", "pallas_call"):
            for arg in node.args[:1]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        jit_names.add(sub.id)

    def qual_of(node):
        parent = ctx.qualname(node)
        return f"{parent}.{node.name}" if parent else node.name

    # -- classes + self.attr constructor types ------------------------------
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            q = qual_of(node)
            methods = [c.name for c in node.body
                       if isinstance(c, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            classes[q] = {"methods": methods, "attr_types": {}}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call):
            continue
        ctor = call_name(node.value)
        klass = ctx.class_of.get(id(node))
        if klass is None or ctor is None:
            continue
        for t in flat_targets(node):
            d = dotted(t) or ""
            if d.startswith("self.") and d.count(".") == 1:
                cq = qual_of(klass)
                if cq in classes:
                    classes[cq]["attr_types"][d.split(".", 1)[1]] = ctor

    # -- per-function walk: calls / syncs / raw writes ----------------------
    def visit(node, fn_stack, locked):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = qual_of(child)
                decs = _decorator_names(child)
                body_src = " ".join(
                    expr_text(n.test) for n in ast.walk(child)
                    if isinstance(n, ast.If))
                funcs[q] = {
                    "name": child.name,
                    "lineno": child.lineno,
                    "cls": (qual_of(ctx.class_of[id(child)])
                            if ctx.class_of.get(id(child)) is not None
                            and ctx.func_of.get(id(child))
                            is ctx.func_of.get(id(ctx.class_of[id(child)]))
                            else None),
                    "jitted": (child.name in jit_names
                               or any(dn.split(".")[-1] in ("jit", "pjit")
                                      for dn in decs)),
                    "memo_guard": (bool(_MEMO_TEST_RE.search(body_src))
                                   or any(dn.split(".")[-1] in
                                          ("lru_cache", "cache")
                                          for dn in decs)),
                    "params": sorted(_param_names(child)),
                    "calls": [],
                    "syncs": [],
                    "raw_writes": [],
                }
                # a function DEFINED under a lock does not RUN under it
                visit(child, fn_stack + [(q, child)], False)
                continue
            if isinstance(child, ast.Lambda):
                # same rule for lambdas: one defined under `with lock:`
                # can be stored and invoked later, off-lock (the
                # deferred-callback shape) — recording its calls as
                # locked would let always_locked() prove a helper safe
                # that actually races; unlocked is the safe direction
                visit(child, fn_stack, False)
                continue
            child_locked = locked
            if isinstance(child, (ast.With, ast.AsyncWith)) and any(
                    _is_lock_with(i) for i in child.items):
                child_locked = True
            if isinstance(child, ast.Call) and fn_stack:
                q, fn_node = fn_stack[-1]
                _record_call(ctx, funcs[q], fn_node, child, locked,
                             np_aliases, jnp_aliases)
            visit(child, fn_stack, child_locked)

    visit(ctx.tree, [], False)
    module, is_pkg = module_of(ctx.path)
    return {
        "sha": hashlib.sha256(ctx.source.encode("utf-8")).hexdigest(),
        "module": module,
        "is_pkg": is_pkg,
        "mod_alias": dict(ctx.mod_alias),
        "from_imports": {k: list(v) for k, v in ctx.from_imports.items()},
        "functions": funcs,
        "classes": classes,
    }


def _record_call(ctx, summary, fn_node, call, locked, np_aliases,
                 jnp_aliases):
    d = call_name(call)
    if d is not None:
        summary["calls"].append([d, call.lineno, bool(locked)])
    sup = None

    def suppressed(rule):
        nonlocal sup
        if sup is None:
            sup = suppressed_rules(ctx, call.lineno)
        return rule in sup or "all" in sup

    # implicit device→host syncs a one-hop caller inherits
    if (isinstance(call.func, ast.Attribute)
            and call.func.attr in SYNC_ATTRS
            and not call.args and not call.keywords):
        summary["syncs"].append(
            [f".{call.func.attr}()", call.lineno,
             suppressed("sync-point")])
    elif (isinstance(call.func, ast.Name)
          and call.func.id in ("float", "bool", "int") and call.args
          and isinstance(call.args[0], ast.Call)
          and isinstance(call.args[0].func, ast.Attribute)
          and call.args[0].func.attr in SYNC_REDUCTIONS
          and not (isinstance(call.args[0].func.value, ast.Name)
                   and call.args[0].func.value.id in np_aliases)):
        summary["syncs"].append(
            [f"{call.func.id}({expr_text(call.args[0])})", call.lineno,
             suppressed("sync-point")])

    # raw writes of a PARAMETER path (the wrapper-around-open shape).
    # Functions named like the durability layer itself (atomic_write /
    # write_atomic) are the structural allowlist: they ARE tmp+rename
    # commit layers, not bypasses of one.
    if "atomic" in fn_node.name:
        return
    params = _param_names(fn_node)

    def param_in(expr):
        return any(isinstance(n, ast.Name) and n.id in params
                   for n in ast.walk(expr))

    sink, kind = None, None
    if d == "open" and call.args:
        mode = call.args[1] if len(call.args) >= 2 else None
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if mode is not None and any(
                m.startswith("w") for m in strings_in(mode)):
            sink, kind = call.args[0], "open(..., 'w')"
    elif d is not None and d.endswith("pickle.dump") and len(call.args) >= 2:
        sink, kind = call.args[1], "pickle.dump"
    elif d is not None and call.args and any(
            d == f"{a}.{s}" for a in np_aliases
            for s in ("save", "savez", "savez_compressed")):
        sink, kind = call.args[0], d
    if sink is not None and param_in(sink):
        summary["raw_writes"].append(
            [kind, call.lineno, suppressed("durability")])


# ---------------------------------------------------------------------------
# the linked index
# ---------------------------------------------------------------------------
class ProjectIndex:
    """Linked phase-1 output.  Build with :func:`build_index` (from
    FileCtx objects) or :meth:`from_json` (the serialized cache), then
    query from the passes."""

    def __init__(self, files=None):
        self.files = files or {}   # rel -> summarize_file() dict
        self._linked = False

    # -- construction -------------------------------------------------------
    def add_file(self, rel, summary):
        self.files[rel] = summary
        self._linked = False

    def remove_file(self, rel):
        """Drop a file that left the tree (deleted/renamed) so its stale
        summary cannot keep discharging proofs or feeding reachability."""
        self.files.pop(rel, None)
        self._linked = False

    def link(self):
        if self._linked:
            return self
        self.module_map = {}       # dotted module -> rel
        for rel, info in self.files.items():
            self.module_map[info["module"]] = rel
        # unique-method table (last-resort receiver-less resolution)
        counts = {}
        for rel, info in self.files.items():
            for cq, cinfo in info["classes"].items():
                for m in cinfo["methods"]:
                    counts.setdefault(m, []).append((rel, f"{cq}.{m}"))
        self.unique_methods = {m: v[0] for m, v in counts.items()
                               if len(v) == 1 and m not in COMMON_METHODS}
        # resolve every call site -> edges + callers map
        self.edges = {}            # (rel, qual) -> [(rel2, qual2, lineno)]
        self.callers = {}          # (rel2, qual2) -> [((rel, qual), locked)]
        for rel, info in self.files.items():
            for qual, fs in info["functions"].items():
                fid = (rel, qual)
                out = []
                for text, lineno, locked in fs["calls"]:
                    tgt = self.resolve_call(rel, qual, text)
                    if tgt is None or tgt == fid:
                        continue
                    out.append((tgt[0], tgt[1], lineno))
                    self.callers.setdefault(tgt, []).append((fid, locked))
                self.edges[fid] = out
        self._locked_memo = {}
        self._hot = None
        self._emit_memo = {}
        self._linked = True
        return self

    # -- symbol resolution --------------------------------------------------
    def _function(self, rel, qual):
        info = self.files.get(rel)
        return info["functions"].get(qual) if info else None

    def _resolve_symbol(self, rel, name, depth=0):
        """`name` looked up in module `rel`: a function, a class (→ its
        __init__ / the class qual), a submodule, or a re-export."""
        if depth > 6 or rel not in self.files:
            return None
        info = self.files[rel]
        if name in info["functions"]:
            return ("func", rel, name)
        if name in info["classes"]:
            return ("class", rel, name)
        # submodule file?
        sub = f"{info['module']}.{name}" if info["module"] else name
        if sub in getattr(self, "module_map", {}):
            return ("module", self.module_map[sub], sub)
        # re-export: `from .x import name` at module level
        fi = info["from_imports"].get(name)
        if fi is not None:
            mod_rel = self._resolve_module(rel, fi[0])
            if mod_rel is not None:
                got = self._resolve_symbol(mod_rel, fi[1], depth + 1)
                if got is not None:
                    return got
                # the imported NAME may itself be a submodule of fi[0]
                minfo = self.files.get(mod_rel)
                if minfo is not None:
                    sub = f"{minfo['module']}.{fi[1]}"
                    if sub in self.module_map:
                        return ("module", self.module_map[sub], sub)
        mod = info["mod_alias"].get(name)
        if mod is not None and mod in self.module_map:
            return ("module", self.module_map[mod], mod)
        return None

    def _resolve_module(self, rel, dotted_mod):
        """A (possibly relative) module string from file `rel` -> rel of
        the module file, or None when it's not part of the scan set."""
        info = self.files.get(rel)
        if info is None:
            return None
        level = len(dotted_mod) - len(dotted_mod.lstrip("."))
        tail = dotted_mod.lstrip(".")
        if level:
            parts = info["module"].split(".") if info["module"] else []
            keep = len(parts) - level + (1 if info["is_pkg"] else 0)
            if keep < 0:
                return None
            base = parts[:keep]
            full = ".".join(base + ([tail] if tail else []))
        else:
            full = tail
        return self.module_map.get(full)

    def resolve_call(self, rel, caller_qual, text):
        """Call-site text -> (rel, qualname) of the target function, or
        None (external / unresolvable — contributes no edge)."""
        info = self.files.get(rel)
        if info is None or not text:
            return None
        parts = text.split(".")

        def as_func(kind_tuple):
            if kind_tuple is None:
                return None
            kind, r2, n2 = kind_tuple
            if kind == "func":
                return (r2, n2)
            if kind == "class":
                init = f"{n2}.__init__"
                if init in self.files[r2]["functions"]:
                    return (r2, init)
            return None

        # self.m() — same-class method
        if parts[0] == "self" and len(parts) == 2:
            fs = info["functions"].get(caller_qual)
            cls = fs.get("cls") if fs else None
            if cls and parts[1] in info["classes"].get(
                    cls, {}).get("methods", ()):
                return (rel, f"{cls}.{parts[1]}")
            return self.unique_methods.get(parts[1])
        # self.attr.m() — via constructor-typed attributes
        if parts[0] == "self" and len(parts) == 3:
            fs = info["functions"].get(caller_qual)
            cls = fs.get("cls") if fs else None
            ctor = info["classes"].get(cls, {}).get(
                "attr_types", {}).get(parts[1]) if cls else None
            if ctor is not None:
                got = self._resolve_path(rel, ctor.split("."))
                if got is not None and got[0] == "class":
                    r2, cq = got[1], got[2]
                    if parts[2] in self.files[r2]["classes"].get(
                            cq, {}).get("methods", ()):
                        return (r2, f"{cq}.{parts[2]}")
            return self.unique_methods.get(parts[2])
        if len(parts) == 1:
            name = parts[0]
            # lexically nested helper (closures): nearest enclosing scope
            prefix = caller_qual
            while prefix:
                cand = f"{prefix}.{name}"
                if cand in info["functions"]:
                    return (rel, cand)
                prefix = prefix.rpartition(".")[0]
            return as_func(self._resolve_symbol(rel, name))
        # dotted: resolve the head to a module/class, descend
        got = self._resolve_path(rel, parts)
        if got is not None:
            if got[0] in ("func", "class"):
                return as_func(got)
            if got[0] == "method":
                return (got[1], got[2])
        if parts[0] != "self" and not info["mod_alias"].get(parts[0]):
            return self.unique_methods.get(parts[-1])
        return None

    def _resolve_path(self, rel, parts):
        """Resolve a dotted name path: descend through modules, stopping
        at a function, class, or class method.  Returns a ('func'|'class'
        |'module', rel, name) tuple, ('method', rel, qual), or None."""
        got = self._resolve_symbol(rel, parts[0])
        i = 1
        while got is not None and i < len(parts):
            kind, r2, n2 = got
            if kind == "module":
                got = self._resolve_symbol(r2, parts[i])
                i += 1
            elif kind == "class" and i == len(parts) - 1:
                if parts[i] in self.files[r2]["classes"].get(
                        n2, {}).get("methods", ()):
                    return ("method", r2, f"{n2}.{parts[i]}")
                return None
            else:
                return None
        return got

    # -- lock-context propagation -------------------------------------------
    def always_locked(self, rel, qual):
        """True when EVERY project call chain reaching (rel, qual) holds a
        lock at the boundary — the caller-holds-lock proof."""
        self.link()
        return self._always_locked((rel, qual), set())[0]

    def _always_locked(self, fid, stack):
        """(verdict, provisional).  `provisional` marks a verdict that
        leaned on the optimistic in-cycle assumption for a node still on
        the evaluation stack — correct for the OUTERMOST query (greatest
        fixpoint: a cycle whose only external entries are locked is
        locked) but NOT memoizable: the assumed node may yet resolve
        unlocked, and a cached optimistic True would silently discharge
        a real lock-free mutation.  False is never provisional — the
        optimism only pushes verdicts toward True."""
        if fid in self._locked_memo:
            return self._locked_memo[fid], False
        if fid in stack:
            return True, True  # optimistic on cycles: outer entries decide
        sites = self.callers.get(fid)
        if not sites:
            self._locked_memo[fid] = False
            return False, False
        stack.add(fid)
        ok, provisional = True, False
        for caller, locked in sites:
            if locked:
                continue
            v, p = self._always_locked(caller, stack)
            if not v:
                ok, provisional = False, False
                break
            provisional = provisional or p
        stack.discard(fid)
        if not provisional:
            self._locked_memo[fid] = ok
        return ok, provisional

    def unlocked_entry_chain(self, rel, qual):
        """One call chain entry→…→(rel, qual) holding no lock, for the
        finding message; [] when none is known (no callers at all)."""
        self.link()
        seen = set()

        def walk(fid, chain):
            if fid in seen:
                return None
            seen.add(fid)
            sites = self.callers.get(fid)
            if not sites:
                return chain  # an entry point with no (known) callers
            for caller, locked in sites:
                if locked:
                    continue
                got = walk(caller, [caller[1]] + chain)
                if got is not None:
                    return got
            return None

        got = walk((rel, qual), [])
        return got or []

    # -- hot-path reachability ----------------------------------------------
    def _hot_map(self):
        self.link()
        if self._hot is not None:
            return self._hot
        hot = {}
        queue = []
        for rel, info in self.files.items():
            for root_rel, root_qual in HOT_ROOTS:
                if rel.endswith(root_rel) and root_qual in info["functions"]:
                    fid = (rel, root_qual)
                    hot[fid] = [f"{rel}::{root_qual}"]
                    queue.append(fid)
        while queue:
            fid = queue.pop(0)
            for r2, q2, _ in self.edges.get(fid, ()):
                tgt = (r2, q2)
                if tgt not in hot:
                    hot[tgt] = hot[fid] + [q2]
                    queue.append(tgt)
        self._hot = hot
        return hot

    def hot_chain(self, rel, qual):
        """The call chain from a hot-path root to (rel, qual), or None
        when the function is not reachable from any root."""
        return self._hot_map().get((rel, qual))

    # -- one-hop helper summaries -------------------------------------------
    def callee_summary(self, rel, caller_qual, text):
        """Resolve a call-site text and return (rel2, qual2, summary) of
        the target, or None."""
        self.link()
        tgt = self.resolve_call(rel, caller_qual, text)
        if tgt is None:
            return None
        fs = self._function(*tgt)
        return (tgt[0], tgt[1], fs) if fs is not None else None

    # -- emitter alias closure ----------------------------------------------
    def emitter_aliases(self, rel, home_rel, emitters):
        """(module-alias names, function-alias names) in `rel` that
        resolve — transitively through project re-exports — to the
        catalog's home module (`home_rel`, e.g. tpu_mx/telemetry.py) or
        its emitter functions."""
        self.link()
        key = (rel, home_rel)
        if key in self._emit_memo:
            return self._emit_memo[key]
        mods, funcs = set(), set()
        info = self.files.get(rel)
        if info is None:
            self._emit_memo[key] = (mods, funcs)
            return mods, funcs
        names = set(info["mod_alias"]) | set(info["from_imports"])
        for name in names:
            got = self._resolve_symbol(rel, name)
            if got is None:
                # absolute alias to a module outside the scan set roots
                mod = info["mod_alias"].get(name)
                if mod is not None and self.module_map.get(mod) == home_rel:
                    mods.add(name)
                continue
            kind, r2, n2 = got
            if kind == "module" and r2 == home_rel:
                mods.add(name)
            elif kind == "func" and r2 == home_rel and n2 in emitters:
                funcs.add(name)
        self._emit_memo[key] = (mods, funcs)
        return mods, funcs

    # -- serialization + dirty-region computation ---------------------------
    def to_json(self):
        return {"format": INDEX_FORMAT, "files": self.files}

    @classmethod
    def from_json(cls, payload):
        if not isinstance(payload, dict) \
                or payload.get("format") != INDEX_FORMAT:
            return None  # a stale/foreign cache rebuilds, never crashes
        files = payload.get("files")
        if not isinstance(files, dict):
            return None
        return cls(dict(files))

    def file_edges(self):
        """File-level call-graph edges {rel -> set(rel2)}."""
        self.link()
        out = {rel: set() for rel in self.files}
        for (rel, _), tgts in self.edges.items():
            for r2, _, _ in tgts:
                if r2 != rel:
                    out[rel].add(r2)
        return out

    def dirty_region(self, changed):
        """Files whose analysis verdicts may change when `changed` files
        change: the changed files, their file-level strongly-connected
        components, and direct callers/callees (lock proofs and
        reachability look one resolution step across a file boundary;
        deeper effects are what the full CI run covers)."""
        self.link()
        fwd = self.file_edges()
        rev = {rel: set() for rel in self.files}
        for rel, tgts in fwd.items():
            for t in tgts:
                rev.setdefault(t, set()).add(rel)
        region = {c for c in changed if c in self.files}
        # SCC membership via forward∩backward reachability from each seed
        for seed in list(region):
            down = self._bfs(seed, fwd)
            up = self._bfs(seed, rev)
            region |= (down & up)
        for seed in list(region):
            region |= fwd.get(seed, set())
            region |= rev.get(seed, set())
        return region

    @staticmethod
    def _bfs(seed, graph):
        seen, queue = {seed}, [seed]
        while queue:
            cur = queue.pop(0)
            for nxt in graph.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    queue.append(nxt)
        return seen


def build_index(ctxs):
    """Phase 1 over parsed files: {relpath: FileCtx} -> linked index."""
    idx = ProjectIndex()
    for rel, ctx in ctxs.items():
        idx.add_file(rel, summarize_file(ctx))
    return idx.link()


def read_index(path):
    """Load the serialized index cache; None when absent/stale-format."""
    try:
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    return ProjectIndex.from_json(payload)


def write_index(path, index):
    with open(path, "w", encoding="utf-8") as f:
        json.dump(index.to_json(), f, sort_keys=True)
        f.write("\n")
