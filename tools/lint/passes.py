"""tpumx-lint phase 2: the rule passes.

Every pass runs per file with the shared :class:`~lint.core.FileCtx`
plus (optionally) the phase-1 :class:`~lint.index.ProjectIndex`.  With
no index the passes degrade to the PR-6 lexical behavior — a single
fixture file still lints exactly as before; with the index the
concurrency pass *proves or refutes* caller-holds-lock helpers, the
sync-point and durability passes follow one level of helper
indirection, the telemetry pass sees re-exported emitter aliases, and
the ``hot-path-purity`` pass walks the whole call graph from the
decode/train/fusion roots.  See docs/static_analysis.md for the rule
catalog and the add-a-pass recipe.
"""
from __future__ import annotations

import ast
import re

from .core import (SYNC_ATTRS, SYNC_REDUCTIONS, call_name, const_str,
                   dotted, expr_text, flat_targets, jnp_names, numpy_names,
                   strings_in)
from .index import HOT_ROOTS  # noqa: F401 — re-exported for the CLI/tests

_GUARD_TEST_RE = re.compile(r"isinstance|hasattr|is (not )?None\b")


def func_qual(ctx, node):
    """Qualname of the function enclosing `node` (None at module level)."""
    fn = ctx.func_of.get(id(node))
    if fn is None:
        return None
    parent = ctx.qualname(fn)
    return f"{parent}.{fn.name}" if parent else fn.name


# ---------------------------------------------------------------------------
class Pass:
    """One rule pass.  Subclasses set `name` and implement
    `run(ctx, index=None)` yielding Findings.  Adding a pass = subclass +
    append to build_passes() (docs/static_analysis.md walks through an
    example)."""

    name = None

    def run(self, ctx, index=None):  # pragma: no cover — interface
        raise NotImplementedError


class DurabilityPass(Pass):
    """Raw state writes that bypass checkpoint.atomic_write.

    Flags, in library code (``tpu_mx/``): any ``open(path, "w"/"wb")``,
    any ``pickle.dump(obj, file)``, and ``np.save/np.savez`` to anything
    not provably an in-memory buffer.  In ``tools/``/``bench.py`` only
    *state-shaped* paths are flagged (ones whose expression mentions
    checkpoints/params/states/manifests) — report files there are not
    recovery state.  ``atomic_write``'s own internal ``open`` is the one
    structural allowlist: it IS the durability layer.

    With the project index the pass additionally follows ONE helper hop:
    a call that hands a state-shaped path to a function whose body
    raw-opens its path parameter for write is flagged at the call site —
    the wrapper-around-``open`` blind spot (ISSUE 10).  Helpers named
    like the durability layer itself (``atomic_write``/``write_atomic``,
    i.e. tmp+rename commit layers) are exempt, as are helper sites that
    carry their own justified suppression.
    """

    name = "durability"

    STATE_HINTS = ("params", "states", "checkpoint", "ckpt", "manifest",
                   "capsule", "lastgood")

    def _is_library(self, ctx):
        return ctx.path.startswith("tpu_mx/")

    def _state_shaped(self, arg):
        text = expr_text(arg).lower()
        return any(h in text for h in self.STATE_HINTS)

    def _in_scope(self, ctx, path_arg):
        return self._is_library(ctx) or self._state_shaped(path_arg)

    def _bytesio_fed(self, ctx, call, arg):
        """True when `arg` is (or is assigned from) an io.BytesIO — an
        in-memory sink, no durability contract applies."""
        if any("BytesIO" in (dotted(n) or "")
               for n in ast.walk(arg) if isinstance(n, (ast.Name, ast.Attribute))):
            return True
        if isinstance(arg, ast.Name):
            func = ctx.func_of.get(id(call))
            search = func if func is not None else ctx.tree
            for node in ast.walk(search):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == arg.id
                        for t in node.targets):
                    if "BytesIO" in expr_text(node.value):
                        return True
        return False

    def run(self, ctx, index=None):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = call_name(node)
            # --- open(path, "w"/"wb") --------------------------------
            if fn == "open" and node.args:
                func = ctx.func_of.get(id(node))
                if func is not None and func.name == "atomic_write":
                    continue  # the durability layer's own tmp-file open
                mode = None
                if len(node.args) >= 2:
                    mode = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "mode":
                        mode = kw.value
                if mode is None:
                    continue  # default "r"
                modes = strings_in(mode)
                if not any(m.startswith("w") for m in modes):
                    continue
                if not self._in_scope(ctx, node.args[0]):
                    continue
                yield ctx.finding(
                    self.name, node,
                    f"raw open({expr_text(node.args[0])}, "
                    f"{'/'.join(sorted(set(modes)))}) write bypasses "
                    "checkpoint.atomic_write — a crash mid-write leaves a "
                    "truncated destination (docs/robustness.md)")
            # --- pickle.dump(obj, file) ------------------------------
            elif fn is not None and fn.endswith("pickle.dump"):
                if not self._is_library(ctx) and not (
                        len(node.args) >= 2
                        and self._state_shaped(node.args[1])):
                    continue
                yield ctx.finding(
                    self.name, node,
                    "pickle.dump to a raw file handle bypasses "
                    "checkpoint.atomic_write — use pickle.dumps + "
                    "atomic_write so the commit is all-or-nothing")
            # --- np.save / np.savez(path, ...) -----------------------
            elif fn is not None and node.args and any(
                    fn == f"{alias}.{save}"
                    for alias in numpy_names(ctx)
                    for save in ("save", "savez", "savez_compressed")):
                sink = node.args[0]
                if self._bytesio_fed(ctx, node, sink):
                    continue  # in-memory serialize-then-atomic_write idiom
                if not self._in_scope(ctx, sink):
                    continue
                yield ctx.finding(
                    self.name, node,
                    f"{fn}({expr_text(sink)}, ...) writes state in place — "
                    "serialize to BytesIO and commit via "
                    "checkpoint.atomic_write")
            # --- one helper hop: f(state_path) where f raw-opens -----
            elif fn is not None and index is not None and node.args:
                got = index.callee_summary(ctx.path, func_qual(ctx, node), fn)
                if got is None:
                    continue
                rel2, qual2, fs = got
                writes = [w for w in fs.get("raw_writes", ())
                          if not w[2]]  # unsuppressed helper sites only
                if not writes:
                    continue
                if rel2.startswith("tpu_mx/"):
                    continue  # the helper's own open is flagged directly
                if not any(self._state_shaped(a) for a in node.args):
                    continue
                kind, line2, _ = writes[0]
                yield ctx.finding(
                    self.name, node,
                    f"passes a state-shaped path to {qual2} ({rel2}:"
                    f"{line2}) whose body raw-{kind}s its path parameter "
                    "— a wrapper does not make the write atomic; route "
                    "the commit through checkpoint.atomic_write")


class DeterminismPass(Pass):
    """Library RNG outside the tpu_mx.random process-global state.

    Flags, in ``tpu_mx/`` (the framework's own ``random.py`` excepted):
    draws/seeds on numpy's global stream (``np.random.rand`` etc. —
    route through ``tpu_mx.random.host_rng()`` so the dependence on the
    capsule-covered stream is explicit), fresh ``jax.random.PRNGKey``
    streams (escape the capsule entirely), entropy-seeded
    ``RandomState()``/``default_rng()`` (irreproducible by
    construction), and time-seeded RNG anywhere.  A *seeded* private
    ``RandomState(seed)`` is NOT flagged — that is the blessed pattern
    for iterators that snapshot their own stream via ``state_dict()``.
    """

    name = "determinism"

    GLOBAL_DRAWS = frozenset({
        "seed", "rand", "randn", "randint", "random", "random_sample",
        "ranf", "sample", "uniform", "normal", "standard_normal",
        "shuffle", "permutation", "choice", "beta", "gamma", "binomial",
        "multinomial", "poisson", "exponential", "laplace", "bytes",
    })
    SEEDED_CTORS = ("RandomState", "default_rng")

    def _library(self, ctx):
        return (ctx.path.startswith("tpu_mx/")
                and ctx.path != "tpu_mx/random.py")

    @staticmethod
    def _has_seed_arg(call):
        """True when the RNG constructor receives a non-None seed, either
        positionally or as a keyword (RandomState(seed=7))."""
        if call.args and not (isinstance(call.args[0], ast.Constant)
                              and call.args[0].value is None):
            return True
        return any(not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
                   for kw in call.keywords if kw.arg is not None)

    def _time_seeded(self, node):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                d = call_name(sub) or ""
                if d in ("time.time", "time.time_ns", "time.monotonic",
                         "time.perf_counter"):
                    return True
        return False

    def run(self, ctx, index=None):
        lib = self._library(ctx)
        np_names = numpy_names(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = call_name(node)
            if fn is None:
                continue
            parts = fn.split(".")
            # time-seeded RNG is wrong EVERYWHERE (tools included): the
            # run is irreproducible and the seed is unrecorded.  Both
            # positional and keyword (seed=time.time()) spellings count.
            seedish = list(node.args) + [kw.value for kw in node.keywords]
            if (parts[-1] in ("seed", "PRNGKey", "key", "Random")
                    + self.SEEDED_CTORS
                    and any(self._time_seeded(a) for a in seedish)):
                yield ctx.finding(
                    self.name, node,
                    f"{fn} seeded from wall-clock time — the stream is "
                    "unrecorded and can never be replayed by a resume "
                    "capsule; derive the seed from tpu_mx.random or config")
                continue
            if not lib:
                continue
            # np.random.<draw> on the GLOBAL numpy stream
            if (len(parts) >= 3 and parts[-2] == "random"
                    and parts[-3] in np_names
                    and parts[-1] in self.GLOBAL_DRAWS):
                yield ctx.finding(
                    self.name, node,
                    f"direct {fn} draws from numpy's global stream — "
                    "route through tpu_mx.random.host_rng() (the "
                    "capsule-covered stream) or a private seeded "
                    "RandomState with state_dict coverage")
            # fresh jax PRNGKey/typed-key stream outside tpu_mx/random.py
            # (jax.random.key is the current recommended constructor —
            # same capsule-escape as the legacy PRNGKey)
            elif parts[-1] == "PRNGKey" or (
                    len(parts) >= 2 and parts[-2] == "random"
                    and parts[-1] == "key"):
                yield ctx.finding(
                    self.name, node,
                    f"fresh {parts[-1]} stream escapes the "
                    "process-global tpu_mx.random state — resume capsules "
                    "cannot replay it; use tpu_mx.random.take_key()")
            # entropy-seeded private streams (a seed passed positionally
            # OR as seed=/... keyword makes the stream reproducible)
            elif parts[-1] in self.SEEDED_CTORS and (
                    len(parts) < 3 or parts[-2] == "random") and (
                    not self._has_seed_arg(node)):
                yield ctx.finding(
                    self.name, node,
                    f"{fn} with no seed draws OS entropy — the stream is "
                    "irreproducible; seed it from config or "
                    "tpu_mx.random")


class SyncPointPass(Pass):
    """Implicit device→host syncs inside the hot paths.

    Hot scopes: ``tpu_mx/fusion.py`` and ``tpu_mx/parallel/train_step.py``
    (whole files — segment construction and the step dispatch path), and
    optimizer ``update*``/``create_state*`` bodies.  Flags ``.asnumpy()``
    / ``.item()`` / ``.tolist()`` / ``jax.device_get`` /
    host-``np.asarray(...)`` calls, and ``float()/bool()/int()`` applied
    to a call or subscript result (an array reduction like
    ``float(loss.mean())`` blocks dispatch; ``float(self.lr)`` on plain
    attributes stays silent).  Explicit syncs (``wait_to_read``,
    ``block_until_ready``) are allowed — the contract is that a sync must
    be *visible*, not that it never happens.

    With the project index, a call FROM a hot scope to a helper whose
    body contains an (unsuppressed) implicit sync is flagged at the call
    site — one level of indirection, so hiding the ``.item()`` in a
    same-file or imported helper no longer evades the rule.  Helpers
    that live in a hot scope themselves are skipped (their sites are
    flagged directly), and a justified suppression at the helper site
    covers its callers too.
    """

    name = "sync-point"

    HOT_FILES = ("tpu_mx/fusion.py", "tpu_mx/parallel/train_step.py")
    HOT_FUNC_FILES = ("tpu_mx/optimizer/", )
    HOT_FUNC_PREFIXES = ("update", "_update", "create_state", "step")
    IMPLICIT = SYNC_ATTRS
    # method-style array reductions: float(loss.mean()) blocks on device.
    # Module-level host calls (np.prod(shape)) and dict methods (.get)
    # are host work — the nearest legitimate look-alikes, left silent.
    REDUCTIONS = SYNC_REDUCTIONS

    def _hot(self, ctx, node):
        if ctx.path in self.HOT_FILES:
            return True
        if any(ctx.path.startswith(p) for p in self.HOT_FUNC_FILES):
            func = ctx.func_of.get(id(node))
            while func is not None:
                if any(func.name.startswith(p)
                       for p in self.HOT_FUNC_PREFIXES):
                    return True
                func = ctx.func_of.get(id(func))
        return False

    def run(self, ctx, index=None):
        hot_possible = (ctx.path in self.HOT_FILES
                        or any(ctx.path.startswith(p)
                               for p in self.HOT_FUNC_FILES))
        if not hot_possible:
            return
        np_names = numpy_names(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not self._hot(ctx, node):
                continue
            fn = call_name(node)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in self.IMPLICIT
                    and not node.args and not node.keywords):
                yield ctx.finding(
                    self.name, node,
                    f".{node.func.attr}() forces a device→host sync on the "
                    "hot path — it stalls dispatch and flushes/splits any "
                    "fusion segment; hoist it out or make the sync "
                    "explicit at the loop level")
            elif fn == "jax.device_get" or (
                    fn is not None and "." in fn
                    and fn.split(".")[0] in np_names
                    and fn.split(".")[-1] in ("asarray", "array")
                    and ctx.path in self.HOT_FILES):
                yield ctx.finding(
                    self.name, node,
                    f"{fn}(...) copies device memory to host on the hot "
                    "path — an implicit sync; keep data on device "
                    "(jnp.asarray) or sync explicitly outside the step")
            elif (isinstance(node.func, ast.Name)
                  and node.func.id in ("float", "bool", "int")
                  and node.args
                  and isinstance(node.args[0], ast.Call)
                  and isinstance(node.args[0].func, ast.Attribute)
                  and node.args[0].func.attr in self.REDUCTIONS
                  and not (isinstance(node.args[0].func.value, ast.Name)
                           and node.args[0].func.value.id in np_names)):
                yield ctx.finding(
                    self.name, node,
                    f"{node.func.id}({expr_text(node.args[0])}) on the hot "
                    "path blocks until the device value materializes — an "
                    "implicit sync point; read it back outside the step "
                    "or keep the value on device")
            elif fn is not None and index is not None:
                got = index.callee_summary(ctx.path, func_qual(ctx, node), fn)
                if got is None:
                    continue
                rel2, qual2, fs = got
                if rel2 in self.HOT_FILES:
                    continue  # the helper's own sites are flagged directly
                syncs = [s for s in fs.get("syncs", ()) if not s[2]]
                if not syncs:
                    continue
                desc, line2, _ = syncs[0]
                yield ctx.finding(
                    self.name, node,
                    f"calls {qual2} ({rel2}:{line2}) whose body forces a "
                    f"device→host sync ({desc}) — one helper hop does not "
                    "hide the stall; hoist the sync out of the hot path "
                    "or justify it at the helper site")


class ConcurrencyPass(Pass):
    """Thread-lifetime and lock-discipline contracts.

    (a) ``threading.Thread(...)`` must pass an explicit ``daemon=``; a
    non-daemon thread must additionally be ``.join()``-ed somewhere in
    the file (otherwise interpreter shutdown can hang on it — the
    watchdog/generation discipline from PR 4).
    (b) Per class: a ``self.X`` attribute that is assigned under a
    ``with self.<lock>:`` block at ANY site must not be assigned
    lock-free at another site (``__init__`` excepted — before the object
    escapes, no thread can see it).  Mixed discipline is exactly the
    zombie-step class of race.
    (c) Per MODULE: a module-level global that is assigned/mutated under
    a ``with <module_lock>:`` block at ANY site must not be mutated
    lock-free in another function (module top level — import time,
    single-threaded — excepted).  Covered mutations: ``global X;
    X = ...``, ``X[...] = ...`` and ``X.attr = ...`` where X is a
    module-level name (plus their aug/annotated forms); method CALLS
    (``X.append(...)``) are not assignments and stay out of scope.

    With the project index, rules (b) and (c) propagate lock context
    through the call graph: a lock-free mutation inside a helper is
    **proven safe** when every project call chain reaching the helper
    holds a lock at the boundary (``ProjectIndex.always_locked`` — the
    caller-holds-lock shape that previously needed a suppression), and
    otherwise the finding names one lock-free entry chain, so a
    transitively-reachable unlocked mutation is a finding with its
    witness path attached.
    """

    name = "concurrency"

    def run(self, ctx, index=None):
        yield from self._threads(ctx)
        yield from self._lock_discipline(ctx, index)
        yield from self._module_lock_discipline(ctx, index)

    @staticmethod
    def _thread_joins(ctx):
        """Receiver texts of `<expr>.join(...)` calls that can plausibly
        be thread joins — string `", ".join` and `os.path.join` (any
        path-module join) are excluded, so they cannot satisfy the
        non-daemon rule vacuously."""
        joins = set()
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"):
                recv = node.func.value
                if isinstance(recv, ast.Constant):
                    continue  # ", ".join(...)
                text = expr_text(recv)
                if text.endswith("path") or ".path" in text:
                    continue  # os.path.join / posixpath.join
                joins.add(text)
        return joins

    def _threads(self, ctx):
        joins = self._thread_joins(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = call_name(node)
            if fn is None:
                continue
            if fn.endswith("threading.Thread"):
                pass
            elif isinstance(node.func, ast.Name):
                # `from threading import Thread [as T]` — resolve the
                # alias; a class merely NAMED Thread from elsewhere is
                # not ours
                mod, orig = ctx.from_imports.get(node.func.id, ("", ""))
                if orig != "Thread" or mod.split(".")[-1] != "threading":
                    continue
            else:
                continue
            daemon = None
            for kw in node.keywords:
                if kw.arg == "daemon":
                    daemon = kw.value
            if daemon is None:
                yield ctx.finding(
                    self.name, node,
                    "threading.Thread without an explicit daemon= — "
                    "decide the lifetime: daemon=True (watchdog-style, "
                    "may die mid-write) or daemon=False with a join")
            elif (isinstance(daemon, ast.Constant)
                  and daemon.value is False and not joins):
                yield ctx.finding(
                    self.name, node,
                    "non-daemon Thread with no .join() anywhere in this "
                    "file — interpreter shutdown will hang on it")

    def _is_lock_with(self, item):
        d = dotted(item.context_expr) or ""
        return d.startswith("self.") and "lock" in d.lower()

    def _discharged(self, ctx, index, site):
        """Caller-holds-lock proof for a lock-free mutation site: every
        project call chain reaching its enclosing function holds a lock
        at the boundary."""
        if index is None:
            return False
        qual = func_qual(ctx, site)
        return qual is not None and index.always_locked(ctx.path, qual)

    def _entry_note(self, ctx, index, site):
        if index is None:
            return ""
        qual = func_qual(ctx, site)
        if qual is None:
            return ""
        chain = index.unlocked_entry_chain(ctx.path, qual)
        if chain:
            return (" — reached lock-free from "
                    f"{' -> '.join(chain + [qual])}")
        return ""

    def _lock_discipline(self, ctx, index):
        for klass in ast.walk(ctx.tree):
            if not isinstance(klass, ast.ClassDef):
                continue
            guarded = {}    # attr -> first guarded-assign node
            unguarded = {}  # attr -> [unguarded-assign nodes]

            def visit(node, locked, in_init):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, ast.ClassDef):
                        continue  # nested class: analyzed on its own
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        # a direct method's nearest enclosing function is
                        # the class's own (None at module level); anything
                        # deeper is a closure inside a method
                        direct = (ctx.class_of.get(id(child)) is klass
                                  and ctx.func_of.get(id(child))
                                  is ctx.func_of.get(id(klass)))
                        # a function DEFINED under a lock does not RUN
                        # under it; a closure inside __init__ still runs
                        # during construction (keeps in_init)
                        visit(child, False,
                              child.name == "__init__" if direct
                              else in_init)
                        continue
                    child_locked = locked
                    if isinstance(child, ast.With) and any(
                            self._is_lock_with(i) for i in child.items):
                        child_locked = True
                    if isinstance(child, (ast.Assign, ast.AugAssign,
                                          ast.AnnAssign)) and not (
                            isinstance(child, ast.AnnAssign)
                            and child.value is None):  # bare annotation
                        for t in flat_targets(child):
                            d = dotted(t) or ""
                            if not d.startswith("self.") or d.count(".") != 1:
                                continue
                            attr = d.split(".", 1)[1]
                            if locked:
                                guarded.setdefault(attr, child)
                            elif not in_init:
                                unguarded.setdefault(attr, []).append(child)
                    visit(child, child_locked, in_init)

            visit(klass, False, False)
            for attr, sites in unguarded.items():
                if attr not in guarded:
                    continue
                g = guarded[attr]
                for site in sites:
                    if self._discharged(ctx, index, site):
                        continue  # every caller provably holds the lock
                    yield ctx.finding(
                        self.name, site,
                        f"self.{attr} is assigned under a lock at "
                        f"{ctx.path}:{g.lineno} but lock-free here"
                        f"{self._entry_note(ctx, index, site)} — mixed "
                        "discipline races exactly like the PR-4 "
                        "zombie-step bug; take the lock (or document why "
                        "this site is single-threaded)")

    # -- (c) module-level lock/global discipline -----------------------------
    def _is_module_lock_with(self, item):
        d = dotted(item.context_expr) or ""
        return d and not d.startswith("self.") and "lock" in d.lower()

    @staticmethod
    def _locals_of(fn):
        """(local names, declared globals) of a function: parameters plus
        bare-Name assignment/loop targets anywhere inside (nested scopes
        included — over-approximating locals under-approximates findings,
        the safe direction for a lexical rule)."""
        if fn is None:
            return frozenset(), frozenset()
        args = fn.args
        params = {a.arg for a in (args.args + args.kwonlyargs
                                  + getattr(args, "posonlyargs", []))}
        if args.vararg:
            params.add(args.vararg.arg)
        if args.kwarg:
            params.add(args.kwarg.arg)
        declared_global, assigned = set(), set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Global):
                declared_global.update(n.names)
            elif isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                for t in flat_targets(n):
                    if isinstance(t, ast.Name):
                        assigned.add(t.id)
            elif isinstance(n, (ast.For, ast.AsyncFor)):
                for t in ast.walk(n.target):
                    if isinstance(t, ast.Name):
                        assigned.add(t.id)
            elif isinstance(n, ast.comprehension):
                for t in ast.walk(n.target):
                    if isinstance(t, ast.Name):
                        assigned.add(t.id)
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    if item.optional_vars is not None:
                        for t in ast.walk(item.optional_vars):
                            if isinstance(t, ast.Name):
                                assigned.add(t.id)
        return params | (assigned - declared_global), declared_global

    def _module_lock_discipline(self, ctx, index):
        mod_globals = set()
        for node in ctx.tree.body:
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                for t in flat_targets(node):
                    if isinstance(t, ast.Name):
                        mod_globals.add(t.id)
        # names declared `global` anywhere also count (first assignment
        # may happen inside a function)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Global):
                mod_globals.update(node.names)
        if not mod_globals:
            return
        guarded = {}    # global name -> first guarded-mutation node
        unguarded = {}  # global name -> [unguarded-mutation nodes]
        locals_cache = {}

        def target_global(t, fn):
            """The module-global name this target mutates, or None."""
            if id(fn) not in locals_cache:
                locals_cache[id(fn)] = self._locals_of(fn)
            local_names, declared_global = locals_cache[id(fn)]
            if isinstance(t, ast.Name):
                # a bare-name rebind targets the module global only
                # under an explicit `global` declaration
                return t.id if (t.id in declared_global
                                and t.id in mod_globals) else None
            node = t
            while isinstance(node, (ast.Subscript, ast.Attribute)):
                node = node.value
            if isinstance(node, ast.Name) and node.id in mod_globals \
                    and node.id not in local_names:
                return node.id
            return None

        def visit(node, locked, exempt, fn):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    # function bodies run post-import (not exempt); a
                    # function DEFINED under a lock does not RUN under it
                    visit(child, False, False, child)
                    continue
                if isinstance(child, ast.ClassDef):
                    # a class BODY executes at import time (exempt like
                    # module level); its methods hit the branch above
                    visit(child, False, exempt, fn)
                    continue
                child_locked = locked
                if isinstance(child, (ast.With, ast.AsyncWith)) and any(
                        self._is_module_lock_with(i) for i in child.items):
                    child_locked = True
                if isinstance(child, (ast.Assign, ast.AugAssign,
                                      ast.AnnAssign)) and not (
                        isinstance(child, ast.AnnAssign)
                        and child.value is None):  # bare annotation
                    for t in flat_targets(child):
                        name = target_global(t, fn)
                        if name is None:
                            continue
                        if locked:
                            guarded.setdefault(name, child)
                        elif not exempt:
                            unguarded.setdefault(name, []).append(child)
                visit(child, child_locked, exempt, fn)

        visit(ctx.tree, False, True, None)
        for name, sites in unguarded.items():
            if name not in guarded:
                continue
            g = guarded[name]
            for site in sites:
                if self._discharged(ctx, index, site):
                    continue  # every caller provably holds the lock
                yield ctx.finding(
                    self.name, site,
                    f"module global {name!r} is mutated under a lock at "
                    f"{ctx.path}:{g.lineno} but lock-free here"
                    f"{self._entry_note(ctx, index, site)} — mixed "
                    "discipline on module-level shared state (the "
                    "checkpoint._intended shape); take the lock (or "
                    "document why this site is single-threaded)")


class TelemetryCatalogPass(Pass):
    """Names at emission sites must be in their static catalog.

    Two catalogs, one discipline (stable names are an API,
    docs/observability.md): metric names at
    ``<telemetry>.counter/gauge/histogram/span(...)`` call sites are
    checked against ``telemetry.KNOWN_METRICS``, and flight-recorder
    event names at ``<tracing>.emit(...)`` call sites against
    ``tracing.KNOWN_EVENTS`` (any alias whose import resolves to the
    respective module, or functions imported from it — with the project
    index the resolution follows re-export chains across modules, so an
    emitter re-exported under another name is still checked).  A literal
    name outside the catalog — even in a branch the obs CI tier never
    executes — fails; a non-literal name is flagged as unverifiable.
    Each catalog's home module is exempt (it manipulates records
    generically).
    """

    name = "telemetry-catalog"

    EMITTERS = frozenset({"counter", "gauge", "histogram", "span"})
    TRACE_EMITTERS = frozenset({"emit"})

    def __init__(self, known_metrics, known_events=None):
        self.known = known_metrics
        self.known_events = known_events

    @staticmethod
    def _aliases(ctx, module, emitters):
        mods = {alias for alias, mod in ctx.mod_alias.items()
                if mod.split(".")[-1] == module}
        # `from tpu_mx import telemetry [as _telemetry]` — the module is
        # the imported NAME here, not the from-module path
        mods |= {alias for alias, (_, name) in ctx.from_imports.items()
                 if name == module}
        funcs = {alias for alias, (mod, name) in ctx.from_imports.items()
                 if name in emitters and mod.split(".")[-1] == module}
        return mods, funcs

    def _check(self, ctx, module, emitters, known, catalog_name, index):
        if ctx.path == f"tpu_mx/{module}.py" or known is None:
            return
        mods, funcs = self._aliases(ctx, module, emitters)
        if index is not None:
            imods, ifuncs = index.emitter_aliases(
                ctx.path, f"tpu_mx/{module}.py", emitters)
            mods, funcs = mods | imods, funcs | ifuncs
        if not mods and not funcs:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            is_emit = False
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in emitters
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in mods):
                is_emit = True
            elif isinstance(node.func, ast.Name) and node.func.id in funcs:
                is_emit = True
            if not is_emit or not node.args:
                continue
            name = const_str(node.args[0])
            if name is None:
                yield ctx.finding(
                    self.name, node,
                    f"name {expr_text(node.args[0])!r} is not a string "
                    f"literal — {catalog_name} cannot verify it "
                    "statically; emit a literal name (labels/payload "
                    "fields carry the dynamic part)")
            elif name not in known:
                yield ctx.finding(
                    self.name, node,
                    f'name "{name}" is not in {catalog_name} — '
                    "dashboards and the black-box schema will never see "
                    "it; add it to the catalog (and "
                    "docs/observability.md) or fix the typo")

    def run(self, ctx, index=None):
        yield from self._check(ctx, "telemetry", self.EMITTERS,
                               self.known, "telemetry.KNOWN_METRICS", index)
        yield from self._check(ctx, "tracing", self.TRACE_EMITTERS,
                               self.known_events, "tracing.KNOWN_EVENTS",
                               index)


class HotPathPurityPass(Pass):
    """No eager host↔device traffic reachable from a hot-path root.

    The decode/train/fusion inner loops (``lint.index.HOT_ROOTS``: the
    serving engine's decode step, ``decode_attention``, the compiled
    train step, the fusion flush) run per token / per step; an eager
    conversion hiding ANY number of helper hops below them is a per-call
    dispatch cliff — the exact shape PR 9 had to find empirically
    (~73 µs per eager ``jnp.asarray`` operand on the decode path).  The
    pass walks every function the project call graph reaches from a
    root and flags:

    - ``jnp.asarray``/``jnp.array`` outside a jit boundary (an eager
      device commit; inside a jitted function it is a trace-time no-op);
    - ``np.asarray``/``np.array`` applied to a device value (a call
      into ``tpu_mx/kernels/`` or a jitted function, or a local assigned
      from one) — a blocking device→host readback;
    - ``.item()``/``.tolist()``/``.asnumpy()`` — the same readback,
      scalar-shaped;
    - ``jax.device_get``;
    - ``jax.jit(...)`` construction inside the hot region — a fresh jit
      wrapper per call retraces every call.

    Stays silent on: jitted functions and lambdas passed to
    ``jax.jit``/``pallas_call`` (the jit boundary IS the commit point —
    operands cross on the C++ fast path); conversions — eager commits
    AND device readbacks alike — inside an ``isinstance``/``hasattr``-
    tested branch (the guarded-fallback idiom: a guarded fast path
    exists, only foreign inputs pay) or an ``is None`` branch /
    ``lru_cache`` function (memoized construction, runs once); and
    everything not reachable from a root.  Findings carry the witness
    call chain from the root.
    """

    name = "hot-path-purity"

    def _jit_lambda_ids(self, ctx):
        """Lambda nodes passed (possibly nested) to jax.jit/pallas_call —
        their bodies are traced, not executed eagerly."""
        out = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            base = (call_name(node) or "").split(".")[-1]
            if base in ("jit", "pjit", "pallas_call"):
                for arg in node.args[:1]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Lambda):
                            out.add(id(sub))
        return out

    def _device_taint(self, ctx, index, fn_node, qual):
        """(value names assigned from device-producing calls, callable
        names bound to kernel/jitted functions) inside one function."""
        vals, fns = set(), set()

        def producing(call):
            d = call_name(call)
            if d is None:
                return False
            head = d.split(".")[0]
            if head in jnp_names(ctx) or head == "jax":
                return True
            if isinstance(call.func, ast.Name) and call.func.id in fns:
                return True
            tgt = index.resolve_call(ctx.path, qual, d)
            if tgt is None:
                return False
            rel2, qual2 = tgt
            fs = index.files[rel2]["functions"].get(qual2, {})
            return "/kernels/" in rel2 or fs.get("jitted", False)

        def kernel_ref(expr):
            for sub in ast.walk(expr):
                d = dotted(sub) if isinstance(
                    sub, (ast.Name, ast.Attribute)) else None
                if d is None or isinstance(sub, ast.Call):
                    continue
                tgt = index.resolve_call(ctx.path, qual, d)
                if tgt is not None and ("/kernels/" in tgt[0]
                                        or index.files[tgt[0]]["functions"]
                                        .get(tgt[1], {}).get("jitted")):
                    return True
            return False

        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Assign):
                continue
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if not names:
                continue
            if isinstance(node.value, ast.Call):
                if producing(node.value):
                    vals.update(names)
            elif kernel_ref(node.value):
                fns.update(names)
        return vals, fns

    def run(self, ctx, index=None):
        if index is None:
            return
        jit_lambdas = self._jit_lambda_ids(ctx)
        jnp_aliases = jnp_names(ctx)
        np_aliases = numpy_names(ctx)
        info = index.files.get(ctx.path, {"functions": {}})

        for fn_node in ast.walk(ctx.tree):
            if not isinstance(fn_node, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                continue
            parent = ctx.qualname(fn_node)
            qual = f"{parent}.{fn_node.name}" if parent else fn_node.name
            chain = index.hot_chain(ctx.path, qual)
            if chain is None:
                continue
            summary = info["functions"].get(qual, {})
            if summary.get("jitted"):
                continue  # the jit boundary IS the hot path's commit point
            where = f" [hot path: {' -> '.join(chain)}]"
            taint_vals, taint_fns = self._device_taint(
                ctx, index, fn_node, qual)
            yield from self._walk(ctx, index, fn_node, fn_node, qual,
                                  jit_lambdas, jnp_aliases, np_aliases,
                                  taint_vals, taint_fns, summary, where,
                                  guarded=False)

    def _walk(self, ctx, index, fn_node, node, qual, jit_lambdas,
              jnp_aliases, np_aliases, taint_vals, taint_fns, summary,
              where, guarded):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # separate function: checked via its own chain
            if isinstance(child, ast.Lambda) and id(child) in jit_lambdas:
                continue  # traced body, not eager execution
            child_guarded = guarded
            if isinstance(child, ast.If) and _GUARD_TEST_RE.search(
                    expr_text(child.test)):
                child_guarded = True
            if isinstance(child, ast.Call):
                yield from self._check_call(
                    ctx, index, child, qual, jnp_aliases, np_aliases,
                    taint_vals, taint_fns, summary, where, guarded)
            yield from self._walk(ctx, index, fn_node, child, qual,
                                  jit_lambdas, jnp_aliases, np_aliases,
                                  taint_vals, taint_fns, summary, where,
                                  child_guarded)

    def _check_call(self, ctx, index, node, qual, jnp_aliases, np_aliases,
                    taint_vals, taint_fns, summary, where, guarded):
        fn = call_name(node)
        parts = fn.split(".") if fn else []
        # scalar/host readbacks
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in SYNC_ATTRS
                and not node.args and not node.keywords):
            yield ctx.finding(
                self.name, node,
                f".{node.func.attr}() forces a device→host readback on "
                f"a hot-path helper chain{where}")
            return
        if fn == "jax.device_get":
            yield ctx.finding(
                self.name, node,
                f"jax.device_get copies device memory to host inside the "
                f"hot region{where}")
            return
        # eager device commit: jnp.asarray/jnp.array outside a jit
        if (len(parts) == 2 and parts[0] in jnp_aliases
                and parts[1] in ("asarray", "array") and not guarded):
            yield ctx.finding(
                self.name, node,
                f"eager {fn}(...) commits a host value to device per call "
                "(~tens of µs of dispatch each — the PR-9 decode cliff); "
                "pass the raw operand through the jit boundary instead "
                f"(C++ fast path){where}")
            return
        # host readback of a device value: np.asarray(kernel_call(...)).
        # `guarded` exempts the guarded-fallback idiom exactly like the
        # eager-commit check above: `if not isinstance(out, np.ndarray):
        # out = np.asarray(out)` is the documented shape for a helper
        # that serves both host- and device-valued callers — the numpy
        # fast path pays nothing, only genuinely device-valued results
        # pay the (deliberate, branch-visible) readback
        if (len(parts) == 2 and parts[0] in np_aliases
                and parts[1] in ("asarray", "array") and node.args
                and not guarded):
            arg = node.args[0]
            tainted = False
            if isinstance(arg, ast.Call):
                d = call_name(arg)
                head = d.split(".")[0] if d else ""
                if head in jnp_aliases or head == "jax" or (
                        isinstance(arg.func, ast.Name)
                        and arg.func.id in taint_fns):
                    tainted = True
                elif d is not None:
                    tgt = index.resolve_call(ctx.path, qual, d)
                    if tgt is not None and (
                            "/kernels/" in tgt[0]
                            or index.files[tgt[0]]["functions"]
                            .get(tgt[1], {}).get("jitted")):
                        tainted = True
            else:
                base = arg
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                if isinstance(base, ast.Name) and base.id in taint_vals:
                    tainted = True
            if tainted:
                yield ctx.finding(
                    self.name, node,
                    f"{fn}({expr_text(node.args[0])}) reads a device "
                    "value back to host — a blocking sync inside the hot "
                    f"region; keep the value on device{where}")
            return
        # uncached jit construction per call
        if parts and parts[-1] in ("jit", "pjit") and (
                fn in ("jax.jit", "jax.pjit")
                or (isinstance(node.func, ast.Name) and ctx.from_imports
                    .get(node.func.id, ("", ""))[1] in ("jit", "pjit"))):
            if not summary.get("memo_guard"):
                yield ctx.finding(
                    self.name, node,
                    "jax.jit(...) constructed inside the hot region with "
                    "no memoization guard — a fresh wrapper retraces on "
                    "every call; build it once (module-level, lru_cache, "
                    f"or an `is None` guard){where}")


# ---------------------------------------------------------------------------
def build_passes(known_metrics, known_events=None):
    return [DurabilityPass(), DeterminismPass(), SyncPointPass(),
            ConcurrencyPass(),
            TelemetryCatalogPass(known_metrics, known_events),
            HotPathPurityPass()]
