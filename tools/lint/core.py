"""tpumx-lint core: findings, the per-file context, suppressions,
baseline I/O, and static catalog extraction.

Everything here is shared between phase 1 (the project index,
``tools/lint/index.py``) and phase 2 (the rule passes,
``tools/lint/passes.py``).  Pure stdlib; the linter never imports
``tpu_mx`` (catalogs are extracted by *parsing* their home modules).
"""
from __future__ import annotations

import ast
import hashlib
import json
import os
import re

LINT_FORMAT = "tpumx-lint-baseline-v1"

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the default scan set (ISSUE 6): the library, the tools, the bench driver
DEFAULT_TARGETS = ("tpu_mx", "tools", "bench.py")

_SUPPRESS_RE = re.compile(
    r"#\s*tpumx-lint:\s*disable="
    r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------
class Finding:
    """One rule violation at a source location."""

    __slots__ = ("rule", "path", "line", "col", "message", "context",
                 "line_text")

    def __init__(self, rule, path, line, col, message, context="",
                 line_text=""):
        self.rule = rule
        self.path = path            # repo-relative, forward slashes
        self.line = line            # 1-based
        self.col = col              # 0-based
        self.message = message
        self.context = context      # enclosing Class.def qualname ("" = module)
        self.line_text = line_text

    def fingerprint(self):
        """Stable identity for baselining: hashes the rule, file, enclosing
        scope and the normalized source line — NOT the line number, so
        unrelated edits above a baselined finding don't resurrect it."""
        norm = " ".join(self.line_text.split())
        raw = "|".join((self.rule, self.path, self.context, norm))
        return hashlib.sha256(raw.encode("utf-8")).hexdigest()[:16]

    def as_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "context": self.context, "fingerprint": self.fingerprint()}

    def render(self):
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"[{self.rule}] {self.message}")


# ---------------------------------------------------------------------------
# per-file context shared by every pass
# ---------------------------------------------------------------------------
class FileCtx:
    """Parsed file + the lookups the passes share: source lines, a
    node→enclosing-scope map, and the module's import aliases."""

    def __init__(self, path, source):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.scope = {}        # id(node) -> "Class.method" qualname
        self.func_of = {}      # id(node) -> nearest FunctionDef node (or None)
        self.class_of = {}     # id(node) -> nearest ClassDef node (or None)
        self._index_scopes()
        # import aliases: local name -> dotted module it refers to
        self.mod_alias = {}    # e.g. {"np": "numpy", "_telemetry": "...telemetry"}
        self.from_imports = {} # local name -> (module, original name)
        self._index_imports()

    def _index_scopes(self):
        def walk(node, qual, func, klass):
            for child in ast.iter_child_nodes(node):
                q, f, k = qual, func, klass
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = f"{qual}.{child.name}" if qual else child.name
                    f = child
                elif isinstance(child, ast.ClassDef):
                    q = f"{qual}.{child.name}" if qual else child.name
                    k = child
                self.scope[id(child)] = qual
                self.func_of[id(child)] = func
                self.class_of[id(child)] = klass
                walk(child, q, f, k)
        walk(self.tree, "", None, None)

    def _index_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.mod_alias[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                mod = ("." * node.level) + (node.module or "")
                for a in node.names:
                    self.from_imports[a.asname or a.name] = (mod, a.name)

    def qualname(self, node):
        return self.scope.get(id(node), "")

    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule, node, message):
        return Finding(rule, self.path, node.lineno, node.col_offset,
                       message, context=self.qualname(node),
                       line_text=self.line_text(node.lineno))


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------
def dotted(node):
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call):
    return dotted(call.func)


def const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def strings_in(node):
    """Every string constant anywhere inside `node` (e.g. both arms of a
    conditional mode expression)."""
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


def expr_text(node):
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover — unparse handles all real exprs
        return ""


def numpy_names(ctx):
    """Local aliases that refer to the host numpy module."""
    return {alias for alias, mod in ctx.mod_alias.items()
            if mod in ("numpy", "numpy.random")} | {"np", "onp", "_np"}


def jnp_names(ctx):
    """Local aliases that refer to jax.numpy (the device-array module)."""
    return {alias for alias, mod in ctx.mod_alias.items()
            if mod == "jax.numpy"} | {"jnp"}


# Implicit device→host sync markers, shared by phase 1 (summaries) and
# phase 2 (sync-point, hot-path-purity): ONE list, so a new sync attr
# can never make the summaries and the passes disagree on what counts.
SYNC_ATTRS = ("asnumpy", "item", "tolist", "asscalar")
SYNC_REDUCTIONS = frozenset({"mean", "sum", "max", "min", "norm", "prod",
                             "all", "any", "dot"})


def flat_targets(node):
    """Assignment targets of Assign/AugAssign/AnnAssign, tuples flattened."""
    targets = node.targets if isinstance(node, ast.Assign) else [node.target]
    flat = []
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            flat.extend(t.elts)
        else:
            flat.append(t)
    return flat


# ---------------------------------------------------------------------------
# catalog extraction (static — never imports tpu_mx)
# ---------------------------------------------------------------------------
def _load_catalog(repo, module, var):
    """Extract a literal catalog assignment from tpu_mx/<module>.py by
    parsing it — no package import, so the linter needs no jax and runs
    anywhere.  Dict literals yield their key set."""
    path = os.path.join(repo, "tpu_mx", f"{module}.py")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == var
                for t in node.targets):
            value = node.value
            if (isinstance(value, ast.Call)
                    and (dotted(value.func) == "frozenset")
                    and value.args):
                value = value.args[0]
            try:
                return frozenset(ast.literal_eval(value))
            except ValueError:
                return None
    return None


def load_known_metrics(repo=REPO):
    """KNOWN_METRICS from tpu_mx/telemetry.py (statically parsed)."""
    return _load_catalog(repo, "telemetry", "KNOWN_METRICS")


def load_known_events(repo=REPO):
    """KNOWN_EVENTS names from tpu_mx/tracing.py (statically parsed;
    the catalog is a dict of name -> typed payload fields — the event
    NAMES are what emit() call sites are checked against)."""
    return _load_catalog(repo, "tracing", "KNOWN_EVENTS")


# ---------------------------------------------------------------------------
# suppression + baseline
# ---------------------------------------------------------------------------
def suppressed_rules(ctx, lineno):
    """Rules disabled for `lineno` via an inline comment on the line, or
    anywhere in the contiguous comment-only block directly above it (so a
    multi-line justification can lead with the directive)."""
    rules = set()

    def collect(text):
        m = _SUPPRESS_RE.search(text)
        if m:
            rules.update(r.strip() for r in m.group(1).split(",")
                         if r.strip())

    collect(ctx.line_text(lineno))
    ln = lineno - 1
    while ln >= 1 and ctx.line_text(ln).lstrip().startswith("#"):
        collect(ctx.line_text(ln))
        ln -= 1
    return rules


def read_baseline(path):
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return set()
    except ValueError as e:
        raise SystemExit(f"tpumx-lint: baseline {path} unreadable: {e}")
    if data.get("format") != LINT_FORMAT:
        raise SystemExit(f"tpumx-lint: baseline {path}: unknown format "
                         f"{data.get('format')!r}")
    return {e["fingerprint"] for e in data.get("findings", [])}


def write_baseline(path, findings):
    entries = [{"fingerprint": f.fingerprint(), "rule": f.rule,
                "path": f.path, "context": f.context,
                "line": f.line, "message": f.message}
               for f in findings]
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    payload = {"format": LINT_FORMAT,
               "note": "Accepted pre-existing findings; regenerate with "
                       "tools/tpumx_lint.py --write-baseline.  Keep this "
                       "EMPTY: prefer a fix, or an inline justified "
                       "'# tpumx-lint: disable=<rule> -- why'.",
               "findings": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
