"""tpumx-lint driver: the two-phase analyzer CLI.

Phase 1 parses every target file once and builds the project index
(``tools/lint/index.py``); phase 2 re-uses the same parsed trees to run
the rule passes (``tools/lint/passes.py``) with the index in hand.  The
index is serialized next to the baseline
(``tools/tpumx_lint_index.json``) so ``--changed-only`` can re-summarize
just the files git reports dirty and re-analyze their call-graph region
— the pre-commit fast path; the full run stays the CI truth.

Exit status: 0 when every finding is suppressed or baselined, 1
otherwise, 2 on usage/internal error (missing targets, unparsable
catalogs, git failure under ``--changed-only`` — the tool fails CLOSED).
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import time

from .core import (DEFAULT_TARGETS, REPO, FileCtx, load_known_events,
                   load_known_metrics, read_baseline, suppressed_rules,
                   write_baseline)
from .index import (ProjectIndex, build_index, read_index, summarize_file,
                    write_index)
from .passes import build_passes

DEFAULT_INDEX = os.path.join(REPO, "tools", "tpumx_lint_index.json")


def _run_passes(ctx, known_metrics, rules, known_events, index):
    findings, suppressed = [], []
    for p in build_passes(known_metrics, known_events):
        if rules and p.name not in rules:
            continue
        for f in p.run(ctx, index):
            sup = suppressed_rules(ctx, f.line)
            if p.name in sup or "all" in sup:
                suppressed.append(f)
            else:
                findings.append(f)
    return findings, suppressed


def lint_source(source, relpath, known_metrics=None, rules=None,
                known_events=None, index=None):
    """Lint one in-memory file; returns (findings, suppressed) lists.
    `relpath` decides scoping (library vs tools vs hot path), so tests
    can exercise any scope with fixture paths.  A single-file index is
    built when none is passed — same-file interprocedural facts
    (caller-holds-lock proofs, hot-path chains) work on lone fixtures."""
    ctx = FileCtx(relpath, source)
    if index is None:
        index = build_index({ctx.path: ctx})
    return _run_passes(ctx, known_metrics, rules, known_events, index)


def lint_sources(sources, known_metrics=None, rules=None, known_events=None):
    """Lint a dict of {relpath: source} as ONE project: the index spans
    the whole set, so cross-module fixtures (helper chains, re-exported
    emitters) resolve.  Returns (findings, suppressed)."""
    ctxs = {}
    for rel, src in sources.items():
        ctx = FileCtx(rel, src)
        ctxs[ctx.path] = ctx
    index = build_index(ctxs)
    findings, suppressed = [], []
    for rel in sorted(ctxs):
        found, sup = _run_passes(ctxs[rel], known_metrics, rules,
                                 known_events, index)
        findings.extend(found)
        suppressed.extend(sup)
    return findings, suppressed


def iter_files(targets, repo=REPO, missing=None):
    for t in targets:
        full = t if os.path.isabs(t) else os.path.join(repo, t)
        if not os.path.isfile(full) and not os.path.isdir(full) \
                and os.path.exists(t):
            full = os.path.abspath(t)  # relative to CWD, not the repo
        if os.path.isfile(full):
            yield full
        elif not os.path.isdir(full):
            # a typo'd target must NOT read as a clean lint
            if missing is not None:
                missing.append(t)
        elif os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        yield os.path.join(dirpath, fname)


def _parse_targets(targets, repo, errors):
    """Phase 0: read + parse every target file -> {rel: FileCtx}."""
    ctxs, missing = {}, []
    for path in iter_files(targets, repo, missing=missing):
        rel = os.path.relpath(os.path.abspath(path), repo)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            ctx = FileCtx(rel, source)
        except SyntaxError as e:
            errors.append(f"{rel.replace(os.sep, '/')}: syntax error: {e}")
            continue
        except OSError as e:
            errors.append(f"{rel.replace(os.sep, '/')}: unreadable: {e}")
            continue
        ctxs[ctx.path] = ctx
    errors.extend(f"target not found: {t}" for t in missing)
    return ctxs


def lint_paths(targets, repo=REPO, known_metrics=None, rules=None,
               known_events=None, index=None):
    """Two-phase lint of files/dirs: returns (findings, suppressed,
    errors).  Pass a prebuilt `index` to skip phase 1 — phase 2 then
    runs only over `targets` while the index facts span the whole
    project (the --changed-only shape)."""
    errors = []
    ctxs = _parse_targets(targets, repo, errors)
    if index is None:
        index = build_index(ctxs)
    all_findings, all_suppressed = [], []
    for rel in sorted(ctxs):
        found, sup = _run_passes(ctxs[rel], known_metrics, rules,
                                 known_events, index)
        all_findings.extend(found)
        all_suppressed.extend(sup)
    return all_findings, all_suppressed, errors


def git_changed_files(repo=REPO):
    """Repo-relative paths of files git reports modified/added/renamed
    (staged, unstaged and untracked).  Raises SystemExit on git failure
    — --changed-only must fail closed, not lint nothing."""
    try:
        # --untracked-files=all: 'normal' reports a brand-new package as
        # one '?? dir/' line, and dir/ fails the .py filter — every file
        # inside an untracked directory would silently skip the lint
        run = subprocess.run(
            ["git", "-C", repo, "status", "--porcelain",
             "--untracked-files=all"],
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise SystemExit(f"tpumx-lint: --changed-only needs git: {e}")
    if run.returncode != 0:
        raise SystemExit("tpumx-lint: git status failed: "
                         + (run.stderr or "").strip())
    changed = set()
    for line in run.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:]
        if " -> " in path:  # rename: lint the new name
            path = path.split(" -> ", 1)[1]
        path = path.strip().strip('"')
        if path.endswith(".py"):
            changed.add(path.replace(os.sep, "/"))
    return changed


def _changed_only_lint(opts, known, known_events, rules):
    """The pre-commit fast path: sha-validate the cached index against
    the tree (git's dirty bit alone is not enough — a pull or branch
    switch rewrites files git then calls clean), re-summarize only the
    stale files, and parse + analyze only the dirty call-graph region."""
    changed = git_changed_files(opts.repo)
    in_scope = {c for c in changed
                if any(c == t or c.startswith(t.rstrip("/") + "/")
                       for t in opts.targets)}
    deleted = {c for c in in_scope
               if not os.path.isfile(os.path.join(opts.repo, c))}
    errors = []
    cached = read_index(opts.index)
    if cached is None:
        # no usable cache: full phase 1 builds it; the region still
        # restricts phase 2 to the git-dirty files' neighborhood
        ctxs = _parse_targets(opts.targets, opts.repo, errors)
        index = build_index(ctxs)
        stale = (in_scope - deleted) & set(ctxs)
    else:
        # a deleted file's callers/callees need re-analysis (its lock
        # contributions and reachability are gone): collect the
        # neighborhood from the OLD graph, then drop the entry so the
        # stale summary cannot keep discharging proofs
        stale = set()
        if deleted & set(cached.files):
            fwd = cached.file_edges()
            for rel, tgts in fwd.items():
                if tgts & deleted:
                    stale.add(rel)
            for d in deleted:
                stale |= fwd.get(d, set())
                cached.remove_file(d)
        # sha-validate EVERY scanned file against the cache
        seen, missing = set(), []
        for path in iter_files(opts.targets, opts.repo, missing=missing):
            rel = os.path.relpath(
                os.path.abspath(path), opts.repo).replace(os.sep, "/")
            seen.add(rel)
            try:
                with open(path, encoding="utf-8") as f:
                    sha = hashlib.sha256(
                        f.read().encode("utf-8")).hexdigest()
            except OSError as e:
                errors.append(f"{rel}: unreadable: {e}")
                continue
            entry = cached.files.get(rel)
            if entry is None or entry.get("sha") != sha:
                stale.add(rel)
        errors.extend(f"target not found: {t}" for t in missing)
        for rel in set(cached.files) - seen:
            cached.remove_file(rel)  # left the scan set, however it went
        # git-dirty files stay seeded EVERY run (not just the run that
        # refreshes their cache entry): a finding in your working set
        # must keep re-appearing until the file is committed or fixed
        stale |= in_scope - deleted
        stale &= seen
        stale_ctxs = _parse_targets(sorted(stale), opts.repo, errors)
        for rel, ctx in stale_ctxs.items():
            cached.add_file(rel, summarize_file(ctx))
        index = cached.link()
    region = index.dirty_region(stale)
    # phase 2 reads and parses ONLY the region files — the point of the
    # cache (the full default run stays the CI truth)
    findings, suppressed, more = lint_paths(
        sorted(region), repo=opts.repo, known_metrics=known, rules=rules,
        known_events=known_events, index=index)
    errors.extend(more)
    write_index(opts.index, index)
    return findings, suppressed, errors, sorted(region | deleted)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tpumx_lint",
        description="framework-aware static analysis for tpu-mx contracts")
    ap.add_argument("targets", nargs="*", default=list(DEFAULT_TARGETS),
                    help="files/dirs to lint (default: tpu_mx tools "
                         "bench.py)")
    ap.add_argument("--format", choices=("human", "json"), default="human")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    ap.add_argument("--baseline", default=None,
                    help="findings baseline path (default: "
                         "<repo>/tools/tpumx_lint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the baseline")
    ap.add_argument("--changed-only", action="store_true",
                    help="re-analyze only git-dirty files and their "
                         "call-graph region (pre-commit fast path; the "
                         "full run is the CI truth)")
    ap.add_argument("--index", default=None,
                    help="project-index cache path (phase 1 output; "
                         "default: <repo>/tools/tpumx_lint_index.json)")
    ap.add_argument("--repo", default=REPO,
                    help="repository root relative targets resolve "
                         "against (tests use a scratch checkout)")
    opts = ap.parse_args(argv)

    # everything repo-relative derives from --repo: linting another
    # checkout must use ITS catalogs/baseline/index, not the host's
    # (and never clobber the host's warm cache)
    if opts.baseline is None:
        opts.baseline = os.path.join(opts.repo, "tools",
                                     "tpumx_lint_baseline.json")
    if opts.index is None:
        opts.index = os.path.join(opts.repo, "tools",
                                  "tpumx_lint_index.json")

    if opts.write_baseline and opts.changed_only:
        # a dirty-region run sees only a slice of the findings; writing
        # it as THE baseline would drop every fingerprint outside the
        # region and turn the next full CI run red
        ap.error("--write-baseline needs the full run, not --changed-only")

    rules = None
    if opts.rules:
        rules = {r.strip() for r in opts.rules.split(",") if r.strip()}
        valid = {p.name for p in build_passes(frozenset())}
        unknown = rules - valid
        if unknown:
            ap.error(f"unknown rules: {sorted(unknown)} "
                     f"(valid: {sorted(valid)})")

    known = load_known_metrics(repo=opts.repo)
    known_events = load_known_events(repo=opts.repo)
    if (known is None or known_events is None) \
            and (rules is None or "telemetry-catalog" in rules):
        # failing OPEN here would silently disable the whole catalog
        # pass (e.g. after a refactor that makes KNOWN_METRICS /
        # KNOWN_EVENTS a computed expression the static extractor can't
        # evaluate)
        missing = "KNOWN_METRICS from tpu_mx/telemetry.py" \
            if known is None else "KNOWN_EVENTS from tpu_mx/tracing.py"
        print(f"tpumx-lint: could not extract {missing} — the "
              "telemetry-catalog pass cannot run; keep the catalog a "
              "literal frozenset({...}) / dict and update "
              "load_known_metrics()/load_known_events()", file=sys.stderr)
        return 2

    t0 = time.perf_counter()
    region = None
    if opts.changed_only:
        findings, suppressed, errors, region = _changed_only_lint(
            opts, known, known_events, rules)
    else:
        ctxs_errors = []
        ctxs = _parse_targets(opts.targets, opts.repo, ctxs_errors)
        t_index0 = time.perf_counter()
        index = build_index(ctxs)
        t_index = time.perf_counter() - t_index0
        findings, suppressed, errors = [], [], ctxs_errors
        for rel in sorted(ctxs):
            found, sup = _run_passes(ctxs[rel], known, rules, known_events,
                                     index)
            findings.extend(found)
            suppressed.extend(sup)
        # refresh the serialized index so --changed-only starts warm
        if opts.targets == list(DEFAULT_TARGETS):
            try:
                write_index(opts.index, index)
            except OSError:
                pass  # a read-only checkout still lints
    elapsed = time.perf_counter() - t0

    if opts.write_baseline:
        write_baseline(opts.baseline, findings)
        print(f"tpumx-lint: baselined {len(findings)} finding(s) -> "
              f"{opts.baseline}")
        return 0

    baseline = set() if opts.no_baseline else read_baseline(opts.baseline)
    fresh = [f for f in findings if f.fingerprint() not in baseline]
    baselined = len(findings) - len(fresh)

    if opts.format == "json":
        payload = {
            "findings": [f.as_dict() for f in fresh],
            "baselined": baselined,
            "suppressed": len(suppressed),
            "errors": errors,
            "known_metrics_loaded": known is not None,
            "known_events_loaded": known_events is not None,
            "elapsed_seconds": round(elapsed, 3),
        }
        if region is not None:
            payload["changed_region"] = region
        print(json.dumps(payload, indent=1, sort_keys=True))
    else:
        for f in fresh:
            print(f.render())
        for e in errors:
            print(f"error: {e}")
        scope = (f" over {len(region)} dirty-region file(s)"
                 if region is not None else "")
        print(f"tpumx-lint: {len(fresh)} finding(s), "
              f"{baselined} baselined, {len(suppressed)} suppressed"
              f" in {elapsed:.1f}s{scope}"
              + ("" if known is not None else
                 " [WARNING: KNOWN_METRICS catalog not loaded]"))
    if errors:
        return 2
    return 1 if fresh else 0
