#!/usr/bin/env python
"""Distributed job launcher (reference analog: tools/launch.py over the
dmlc trackers, REF:3rdparty/dmlc-core/tracker/dmlc_tracker/local.py).

The reference booted a parameter-server topology (scheduler + servers +
workers over ZeroMQ).  TPU-native training is SPMD: every process runs the
same program and `jax.distributed.initialize` forms the collective group,
so the launcher's job shrinks to "start N identical processes with the
right bootstrap env" — exactly the reference's `--launcher local` pattern,
minus the server/scheduler roles.

    python tools/launch.py -n 4 python train.py --kv-store dist_sync

Env protocol handed to each worker (mirrors DMLC_* in spirit):
    TPUMX_COORDINATOR   host:port of process 0
    TPUMX_NUM_PROC      world size
    TPUMX_PROC_ID       this process's rank
A worker calls `tpu_mx.kvstore.dist_init()` (or jax.distributed.initialize
directly) to join.  For CPU-simulated multi-worker tests the spawned
processes default to the CPU backend with JAX_PLATFORMS=cpu.
"""
import argparse
import os
import socket
import subprocess
import sys


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main():
    ap = argparse.ArgumentParser(
        description="Launch a local multi-process SPMD job")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--launcher", default="local", choices=["local"],
                    help="multi-host pods boot via their own pod runtime; "
                         "this tool covers the reference's local tracker")
    ap.add_argument("--env", action="append", default=[],
                    help="extra KEY=VAL for the workers")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")

    coord = f"127.0.0.1:{free_port()}"
    procs = []
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env.update(TPUMX_COORDINATOR=coord,
                   TPUMX_NUM_PROC=str(args.num_workers),
                   TPUMX_PROC_ID=str(rank))
        env.setdefault("JAX_PLATFORMS", "cpu")
        for kv in args.env:
            k, _, v = kv.partition("=")
            env[k] = v
        procs.append(subprocess.Popen(args.command, env=env))
    code = 0
    for p in procs:
        code = p.wait() or code
    if code:
        for p in procs:
            if p.poll() is None:
                p.terminate()
    sys.exit(code)


if __name__ == "__main__":
    main()
