#!/usr/bin/env python
"""Distributed job launcher (reference analog: tools/launch.py over the
dmlc trackers, REF:3rdparty/dmlc-core/tracker/dmlc_tracker/{local,ssh}.py).

The reference booted a parameter-server topology (scheduler + servers +
workers over ZeroMQ).  TPU-native training is SPMD: every process runs the
same program and `jax.distributed.initialize` forms the collective group,
so the launcher's job shrinks to "start N identical processes with the
right bootstrap env" — the reference's local and ssh trackers, minus the
server/scheduler roles.

    # local: N processes on this machine
    python tools/launch.py -n 4 python train.py --kv-store dist_sync

    # ssh: one process per host listed in the hostfile (round-robin when
    # n > number of hosts), same env protocol shipped over the ssh command
    python tools/launch.py -n 4 --launcher ssh -H hosts.txt \
        python train.py --kv-store dist_sync

Env protocol handed to each worker (mirrors DMLC_* in spirit):
    TPUMX_COORDINATOR   host:port of process 0
    TPUMX_NUM_PROC      world size
    TPUMX_PROC_ID       this process's rank
A worker calls `tpu_mx.kvstore.dist_init()` (or jax.distributed.initialize
directly) to join.  For CPU-simulated multi-worker tests the spawned
processes default to the CPU backend with JAX_PLATFORMS=cpu.
"""
import argparse
import os
import shlex
import socket
import subprocess
import sys


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker_env(coord, num_proc, rank, extra=()):
    """The bootstrap env protocol for one worker (shared by both trackers)."""
    env = {
        "TPUMX_COORDINATOR": coord,
        "TPUMX_NUM_PROC": str(num_proc),
        "TPUMX_PROC_ID": str(rank),
    }
    for kv in extra:
        k, _, v = kv.partition("=")
        env[k] = v
    return env


def read_hostfile(path):
    """One host per line; '#' comments and blanks ignored (the dmlc ssh
    tracker's hostfile format)."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if line:
                hosts.append(line)
    if not hosts:
        raise ValueError(f"hostfile {path} has no hosts")
    return hosts


def build_ssh_commands(hosts, num_proc, coord, command, env_extra=(),
                       ssh_opts=()):
    """Construct the per-rank ssh argv list (pure — unit-testable without a
    cluster).  Rank r runs on hosts[r % len(hosts)]; the env protocol is
    inlined into the remote command since ssh does not forward arbitrary
    env vars."""
    cmds = []
    for rank in range(num_proc):
        host = hosts[rank % len(hosts)]
        env = worker_env(coord, num_proc, rank, env_extra)
        assigns = " ".join(f"{k}={shlex.quote(v)}"
                           for k, v in sorted(env.items()))
        remote = f"cd {shlex.quote(os.getcwd())} && env {assigns} " + \
            " ".join(shlex.quote(c) for c in command)
        cmds.append((host, ["ssh", "-o", "StrictHostKeyChecking=no",
                            *ssh_opts, host, remote]))
    return cmds


def launch_local(args, coord):
    procs = []
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env.update(worker_env(coord, args.num_workers, rank, args.env))
        env.setdefault("JAX_PLATFORMS", "cpu")
        procs.append(subprocess.Popen(args.command, env=env))
    return procs


def launch_ssh(args, coord):
    import random
    hosts = read_hostfile(args.hostfile)
    # The jax.distributed coordinator runs INSIDE rank 0 — i.e. on hosts[0],
    # not on this launcher machine — so that's the address every rank must
    # dial.  The port can't be probed remotely; pick one from the dynamic
    # range (collision odds are negligible and a clash fails fast).
    port = random.randint(49152, 65535)
    coord = f"{hosts[0]}:{port}"
    cmds = build_ssh_commands(hosts, args.num_workers, coord, args.command,
                              args.env)
    return [subprocess.Popen(argv) for _host, argv in cmds]


def main():
    ap = argparse.ArgumentParser(
        description="Launch a multi-process SPMD job (local or ssh)")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--launcher", default="local", choices=["local", "ssh"])
    ap.add_argument("-H", "--hostfile",
                    help="hosts file for --launcher ssh (one per line)")
    ap.add_argument("--env", action="append", default=[],
                    help="extra KEY=VAL for the workers")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")
    if args.launcher == "ssh" and not args.hostfile:
        ap.error("--launcher ssh requires -H/--hostfile")

    coord = f"127.0.0.1:{free_port()}"
    procs = launch_local(args, coord) if args.launcher == "local" \
        else launch_ssh(args, coord)
    code = 0
    for p in procs:
        code = p.wait() or code
    if code:
        for p in procs:
            if p.poll() is None:
                p.terminate()
    sys.exit(code)


if __name__ == "__main__":
    main()
