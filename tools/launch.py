#!/usr/bin/env python
"""Distributed job launcher (reference analog: tools/launch.py over the
dmlc trackers, REF:3rdparty/dmlc-core/tracker/dmlc_tracker/{local,ssh}.py).

The reference booted a parameter-server topology (scheduler + servers +
workers over ZeroMQ).  TPU-native training is SPMD: every process runs the
same program and `jax.distributed.initialize` forms the collective group,
so the launcher's job shrinks to "start N identical processes with the
right bootstrap env" — the reference's local and ssh trackers, minus the
server/scheduler roles.

    # local: N processes on this machine
    python tools/launch.py -n 4 python train.py --kv-store dist_sync

    # ssh: one process per host listed in the hostfile (round-robin when
    # n > number of hosts), same env protocol shipped over the ssh command
    python tools/launch.py -n 4 --launcher ssh -H hosts.txt \
        python train.py --kv-store dist_sync

Env protocol handed to each worker (mirrors DMLC_* in spirit):
    TPUMX_COORDINATOR   host:port of process 0
    TPUMX_NUM_PROC      world size
    TPUMX_PROC_ID       this process's rank
A worker calls `tpu_mx.kvstore.dist_init()` (or jax.distributed.initialize
directly) to join.  For CPU-simulated multi-worker tests the spawned
processes default to the CPU backend with JAX_PLATFORMS=cpu.

Elastic fleets (`--supervise`, ISSUE 17): the launcher doubles as the
fleet CONTROLLER.  It opens membership epoch 1 admitting ranks 0..N-1,
hands every worker the TPUMX_FLEET_{DIR,MEMBER,LEASE} env protocol
(tpu_mx.parallel.fleet), and then supervises:

- a worker that exits nonzero (preempted, crashed) is evicted at a fresh
  membership epoch immediately — the survivors quiesce at their next step
  boundary and reshard down — and is restarted with jittered exponential
  backoff while its restart budget (`--max-restarts`) lasts; the restarted
  process joins and is admitted at the NEXT epoch (rejoin → reshard up);
- a worker whose heartbeats stop without the process dying (network
  partition, `partition_worker` chaos) is evicted by lease expiry through
  the normal `Fleet.reconcile` path;
- a worker whose budget is exhausted degrades the fleet to the largest
  healthy world size (`fleet.degrade` + flight-recorder black box); if
  that drops below `--min-workers` the job is torn down.

    python tools/launch.py --supervise -n 2 --max-restarts 3 \
        python train.py --kv-store dist_sync
"""
import argparse
import os
import random
import shlex
import socket
import subprocess
import sys
import tempfile
import time


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker_env(coord, num_proc, rank, extra=()):
    """The bootstrap env protocol for one worker (shared by both trackers)."""
    env = {
        "TPUMX_COORDINATOR": coord,
        "TPUMX_NUM_PROC": str(num_proc),
        "TPUMX_PROC_ID": str(rank),
    }
    for kv in extra:
        k, _, v = kv.partition("=")
        env[k] = v
    return env


def read_hostfile(path):
    """One host per line; '#' comments and blanks ignored (the dmlc ssh
    tracker's hostfile format)."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if line:
                hosts.append(line)
    if not hosts:
        raise ValueError(f"hostfile {path} has no hosts")
    return hosts


def build_ssh_commands(hosts, num_proc, coord, command, env_extra=(),
                       ssh_opts=()):
    """Construct the per-rank ssh argv list (pure — unit-testable without a
    cluster).  Rank r runs on hosts[r % len(hosts)]; the env protocol is
    inlined into the remote command since ssh does not forward arbitrary
    env vars."""
    cmds = []
    for rank in range(num_proc):
        host = hosts[rank % len(hosts)]
        env = worker_env(coord, num_proc, rank, env_extra)
        assigns = " ".join(f"{k}={shlex.quote(v)}"
                           for k, v in sorted(env.items()))
        remote = f"cd {shlex.quote(os.getcwd())} && env {assigns} " + \
            " ".join(shlex.quote(c) for c in command)
        cmds.append((host, ["ssh", "-o", "StrictHostKeyChecking=no",
                            *ssh_opts, host, remote]))
    return cmds


def launch_local(args, coord):
    procs = []
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env.update(worker_env(coord, args.num_workers, rank, args.env))
        env.setdefault("JAX_PLATFORMS", "cpu")
        procs.append(subprocess.Popen(args.command, env=env))
    return procs


def _import_fleet():
    """Import the fleet runtime into the LAUNCHER process.  tools/ is not a
    package, so put the repo root on sys.path; force the CPU backend before
    tpu_mx pulls in jax (the launcher must never grab an accelerator the
    workers need)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    from tpu_mx.parallel import fleet as fleet_mod
    from tpu_mx.parallel import fleet_obs as fleet_obs_mod
    from tpu_mx import telemetry, tracing
    return fleet_mod, fleet_obs_mod, telemetry, tracing


def restart_backoff(base, attempt, rng=None):
    """Jittered exponential backoff for worker restart `attempt` (1-based):
    base * 2^(attempt-1), scaled by a uniform [0.5, 1.5) jitter so a batch
    of preempted workers doesn't stampede the coordinator (pure —
    unit-testable)."""
    rng = random if rng is None else rng
    return float(base) * (2 ** (max(1, int(attempt)) - 1)) * \
        (0.5 + rng.random())


def supervise(args, coord):
    """Fleet-supervising local tracker: spawn N workers under the
    membership-epoch protocol, evict/restart/admit on churn, degrade when
    a worker's restart budget runs out.  Returns the process exit code."""
    fleet_mod, fleet_obs, _telemetry, _tracing = _import_fleet()
    fleet_dir = args.fleet_dir or tempfile.mkdtemp(prefix="tpumx_fleet_")
    fleet = fleet_mod.Fleet(fleet_dir, member=None, controller=True,
                            lease=args.lease)
    fleet.advance(world=range(args.num_workers), reason="launch")
    # the controller-side observability plane: merges the workers'
    # shipped snapshots into fleet.* rollups and watches for persistent
    # stragglers (tpu_mx/parallel/fleet_obs.py)
    agg = fleet_obs.FleetAggregator(fleet,
                                    interval=max(0.5, args.lease / 4.0))

    def spawn(rank, *, fresh=False):
        env = dict(os.environ)
        env.update(worker_env(coord, args.num_workers, rank, args.env))
        env.setdefault("JAX_PLATFORMS", "cpu")
        env[fleet_mod.ENV_DIR] = fleet_dir
        env[fleet_mod.ENV_MEMBER] = str(rank)
        env[fleet_mod.ENV_LEASE] = str(args.lease)
        if fresh and not args.keep_chaos:
            # a chaos knob describes a fault to inject once per JOB, not
            # once per incarnation: a restarted worker that re-read
            # preempt_worker_at_step would preempt itself forever
            env.pop("TPUMX_CHAOS", None)
        return subprocess.Popen(args.command, env=env)

    procs = {rank: spawn(rank) for rank in range(args.num_workers)}
    restarts = {rank: 0 for rank in procs}
    pending = {}       # rank -> monotonic time its backoff expires
    exit_codes = {}
    poll = max(0.05, args.lease / 4.0)

    def straggler_note():
        """One-line straggler context for evict/degrade decisions (empty
        when the detector is quiet)."""
        sig = (agg.last or {}).get("signal") or agg.detector.signal
        if not sig.get("straggling"):
            return ""
        return (f" [straggler: rank {sig['rank']} "
                f"+{sig['excess_seconds']:.3f}s/step in "
                f"{sig['dominant_phase'] or '?'} over {sig['steps']} steps]")

    def dump_fleet_box(why):
        """Collect every live worker's shipped events + telemetry into
        the cross-rank black box (best-effort: forensics must never take
        the controller down)."""
        try:
            return fleet_obs.dump_fleet_blackbox(fleet_dir, reason=why,
                                                 aggregator=agg)
        except OSError:
            return None

    def degrade(rank, why):
        world = fleet.world()
        why += straggler_note()
        _tracing.emit("fleet.degrade", world_size=len(world), reason=why)
        # the fleet black box replaces the PR 15 single-process dump at
        # the SAME path (<fleet_dir>/fleet-blackbox.json): the base
        # document is unchanged, the cross-rank section rides on top
        dump_fleet_box(f"fleet degrade: {why} — continuing at world size "
                       f"{len(world)} {world}")
        print(f"launch: {why}; degrading to world size {len(world)}",
              file=sys.stderr)

    def on_failure(rank, rc):
        if rank in fleet.world():
            # snapshot the fleet BEFORE the eviction epoch: the dying
            # rank's last shipped state is still generation-current here
            # and would be excluded as stale one epoch later
            dump_fleet_box(f"worker {rank} exit={rc}{straggler_note()}"
                           f" — evicting")
            fleet.evict(rank, reason=f"exit={rc}")
        if fleet.is_quarantined(rank):
            # quarantine is permanent: a rank voted out for silent data
            # corruption must never be respawned, no matter how much
            # restart budget is left — its silicon (or its stack) lies.
            # This is a degraded-but-deliberate outcome, distinct from a
            # transient eviction (lease expiry / crash), which rejoins.
            exit_codes.setdefault(rank, rc if rc != 0 else 1)
            degrade(rank, f"worker {rank} quarantined for corruption "
                          f"after exit={rc}; refusing restart")
            return
        if restarts[rank] < args.max_restarts:
            restarts[rank] += 1
            backoff = restart_backoff(args.backoff, restarts[rank])
            _tracing.emit("fleet.restart_worker", member=rank,
                          n=restarts[rank], backoff_seconds=backoff)
            _telemetry.counter("fleet.worker_restarts").inc()
            pending[rank] = time.monotonic() + backoff
            print(f"launch: worker {rank} exited {rc}; restart "
                  f"{restarts[rank]}/{args.max_restarts} in "
                  f"{backoff:.2f}s", file=sys.stderr)
        else:
            exit_codes.setdefault(rank, rc)
            degrade(rank, f"worker {rank} restart budget exhausted "
                          f"({args.max_restarts}) after exit={rc}")

    try:
        while procs or pending:
            for rank, p in list(procs.items()):
                rc = p.poll()
                if rc is None:
                    continue
                del procs[rank]
                if rc == 0:
                    exit_codes[rank] = 0
                else:
                    on_failure(rank, rc)
            # lease-expired members (partitioned but process still alive)
            # are evicted by the protocol path, not the exit-code path
            world_before = fleet.world()
            fleet.reconcile()
            if fleet.world() != world_before:
                dump_fleet_box(f"membership changed by reconcile: "
                               f"{world_before} -> {fleet.world()}"
                               f"{straggler_note()}")
            agg.poll()
            for rank, due in list(pending.items()):
                if time.monotonic() < due:
                    continue
                del pending[rank]
                if fleet.is_quarantined(rank):
                    # the quarantine record can land while the rank sits
                    # in restart backoff (e.g. the survivors' vote names
                    # it after its crash) — drop the respawn, same as the
                    # on_failure refusal
                    exit_codes.setdefault(rank, 1)
                    degrade(rank, f"worker {rank} quarantined during "
                                  f"restart backoff; refusing respawn")
                    continue
                procs[rank] = spawn(rank, fresh=True)
                if fleet.wait_member(rank, timeout=args.join_timeout):
                    # reconcile (not admit): the loop's periodic reconcile
                    # may already have admitted the joiner — reconcile is
                    # idempotent where a second admit would burn an epoch
                    fleet.reconcile(reason="rejoin")
                else:
                    p = procs.pop(rank)
                    rc = p.poll()
                    if rc is None:
                        p.terminate()
                        rc = -1
                    on_failure(rank, rc)
            if len(fleet.world()) < args.min_workers and procs:
                degrade(-1, f"healthy world {fleet.world()} below "
                            f"--min-workers {args.min_workers}; aborting")
                raise SystemExit(1)
            if procs or pending:
                time.sleep(poll)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        # final flight record: whatever the workers last shipped, plus
        # the skew timeline and straggler verdict of the whole run
        dump_fleet_box(f"supervise exit{straggler_note()}")
    # signal deaths report negative codes — any nonzero outcome (even a
    # degraded-but-completed run) must surface as a failed launch
    return 1 if any(rc != 0 for rc in exit_codes.values()) else 0


def launch_ssh(args, coord):
    import random
    hosts = read_hostfile(args.hostfile)
    # The jax.distributed coordinator runs INSIDE rank 0 — i.e. on hosts[0],
    # not on this launcher machine — so that's the address every rank must
    # dial.  The port can't be probed remotely; pick one from the dynamic
    # range (collision odds are negligible and a clash fails fast).
    port = random.randint(49152, 65535)
    coord = f"{hosts[0]}:{port}"
    cmds = build_ssh_commands(hosts, args.num_workers, coord, args.command,
                              args.env)
    return [subprocess.Popen(argv) for _host, argv in cmds]


def main():
    ap = argparse.ArgumentParser(
        description="Launch a multi-process SPMD job (local or ssh)")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--launcher", default="local", choices=["local", "ssh"])
    ap.add_argument("-H", "--hostfile",
                    help="hosts file for --launcher ssh (one per line)")
    ap.add_argument("--env", action="append", default=[],
                    help="extra KEY=VAL for the workers")
    ap.add_argument("--supervise", action="store_true",
                    help="elastic-fleet mode: run as membership controller, "
                         "restart preempted workers, admit rejoins at the "
                         "next epoch (local launcher only)")
    ap.add_argument("--fleet-dir",
                    help="membership store directory (default: a tempdir)")
    ap.add_argument("--max-restarts", type=int, default=3,
                    help="per-worker restart budget before degrading")
    ap.add_argument("--min-workers", type=int, default=1,
                    help="abort when the healthy world drops below this")
    ap.add_argument("--backoff", type=float, default=0.5,
                    help="base seconds for jittered exponential restart "
                         "backoff")
    ap.add_argument("--lease", type=float, default=10.0,
                    help="heartbeat lease seconds (liveness horizon)")
    ap.add_argument("--join-timeout", type=float, default=30.0,
                    help="seconds to wait for a restarted worker to join")
    ap.add_argument("--keep-chaos", action="store_true",
                    help="keep TPUMX_CHAOS in restarted workers' env "
                         "(default: injected faults fire once per job)")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="worker command line")
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")
    if args.launcher == "ssh" and not args.hostfile:
        ap.error("--launcher ssh requires -H/--hostfile")
    if args.supervise and args.launcher != "local":
        ap.error("--supervise requires --launcher local")

    coord = f"127.0.0.1:{free_port()}"
    if args.supervise:
        sys.exit(supervise(args, coord))
    procs = launch_local(args, coord) if args.launcher == "local" \
        else launch_ssh(args, coord)
    code = 0
    for p in procs:
        code = p.wait() or code
    if code:
        for p in procs:
            if p.poll() is None:
                p.terminate()
    sys.exit(code)


if __name__ == "__main__":
    main()
