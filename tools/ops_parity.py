"""Op-parity audit (VERDICT r3 ask#6): the upstream MXNet 1.x public op
registry enumerated against this framework, one row per op.

The registry below is the curated public `mx.nd.*` surface of upstream
Apache MXNet 1.x (REF:src/operator/** registrations as exposed through
the Python stubs — the reference mount is empty, so this is the upstream
1.x documented API, the same source SURVEY.md §2.1 used).  Internal
`_backward_*`/`_np_*` registrations are excluded: JAX autodiff subsumes
the former wholesale and `tpu_mx.np` mirrors the latter.

Statuses:
  yes         — implemented; `impl` names the callable (smoke-invoked by
                tests/test_ops_parity.py via the SMOKE templates here)
  divergent   — capability provided through a documented TPU-native
                design divergence (see docs/DIVERGENCES.md); `impl`
                points at the replacement
  not-planned — deliberately absent; `note` says why

Regenerate the markdown after editing ROWS:
    python tools/ops_parity.py > OPS_PARITY.md
tests/test_ops_parity.py asserts OPS_PARITY.md is in sync, every `yes`
row resolves, and every smoke template executes.
"""
from __future__ import annotations

# (op, status, impl, note)
ROWS = {}

ROWS["Neural network (REF:src/operator/nn, *.cc at src/operator/)"] = [
    ("Activation", "yes", "nd.Activation", ""),
    ("BatchNorm", "yes", "nd.BatchNorm", "fused via XLA; batch_norm_core"),
    ("BatchNorm_v1", "yes", "nd.BatchNorm_v1", "deprecated alias; forwards with a DeprecationWarning"),
    ("Convolution", "yes", "nd.Convolution", "lax.conv_general_dilated; NHWC default layout"),
    ("Convolution_v1", "yes", "nd.Convolution_v1", "deprecated alias; forwards with a DeprecationWarning"),
    ("Correlation", "yes", "nd.Correlation",
     "cost volume as a static displacement loop of VPU products + window sums — no gather"),
    ("Deconvolution", "yes", "nd.Deconvolution", "conv_transpose"),
    ("Dropout", "yes", "nd.Dropout", "PRNG via random.key_scope"),
    ("Embedding", "yes", "nd.Embedding", "take; dense grad (divergence #5 covers row_sparse)"),
    ("FullyConnected", "yes", "nd.FullyConnected", ""),
    ("GridGenerator", "yes", "nd.GridGenerator", ""),
    ("GroupNorm", "yes", "nd.GroupNorm", ""),
    ("IdentityAttachKLSparseReg", "yes", "nd.IdentityAttachKLSparseReg",
     "identity fwd + injected KL sparsity grad; moving-average aux rebound in place"),
    ("InstanceNorm", "yes", "nd.InstanceNorm", ""),
    ("L2Normalization", "yes", "nd.L2Normalization", ""),
    ("LRN", "yes", "nd.LRN", ""),
    ("LayerNorm", "yes", "nd.LayerNorm", ""),
    ("LeakyReLU", "yes", "nd.LeakyReLU", "incl. prelu/elu/selu/gelu act types"),
    ("MakeLoss", "yes", "nd.MakeLoss", ""),
    ("Pad", "yes", "nd.Pad", ""),
    ("Pooling", "yes", "nd.Pooling", "max/avg/sum/lp, global, NHWC/NCHW"),
    ("Pooling_v1", "yes", "nd.Pooling_v1", "deprecated alias; forwards with a DeprecationWarning"),
    ("RNN", "yes", "nd.RNN", "fused multi-layer LSTM/GRU/vanilla via lax.scan (the cuDNN-RNN analog)"),
    ("ROIPooling", "yes", "nd.ROIPooling", ""),
    ("SVMOutput", "yes", "nd.SVMOutput", "L1/L2 hinge output head"),
    ("SequenceLast", "yes", "nd.SequenceLast", ""),
    ("SequenceMask", "yes", "nd.SequenceMask", ""),
    ("SequenceReverse", "yes", "nd.SequenceReverse", ""),
    ("SliceChannel", "yes", "nd.SliceChannel", ""),
    ("Softmax", "yes", "nd.Softmax",
     "upstream add_alias of SoftmaxOutput (NOT nd.softmax); forwards with a DeprecationWarning"),
    ("SoftmaxActivation", "yes", "nd.SoftmaxActivation", ""),
    ("SoftmaxOutput", "yes", "nd.SoftmaxOutput", "custom-vjp injected CE gradient"),
    ("SpatialTransformer", "yes", "nd.SpatialTransformer", ""),
    ("SwapAxis", "yes", "nd.SwapAxis", ""),
    ("UpSampling", "yes", "nd.UpSampling", "nearest + bilinear"),
    ("BilinearSampler", "yes", "nd.BilinearSampler", ""),
    ("CTCLoss", "yes", "nd.CTCLoss", "log-semiring scan; torch-checked"),
    ("BlockGrad", "yes", "nd.BlockGrad", "stop_gradient"),
    ("Custom", "yes", "nd.Custom", "CustomOp/CustomOpProp registry (operator.py)"),
    ("Crop", "yes", "nd.Crop", ""),
    ("LinearRegressionOutput", "yes", "nd.LinearRegressionOutput", ""),
    ("LogisticRegressionOutput", "yes", "nd.LogisticRegressionOutput", ""),
    ("MAERegressionOutput", "yes", "nd.MAERegressionOutput", ""),
    ("Dropout (axes=)", "yes", "nd.Dropout", "structured dropout via axes param"),
]

_UNARY = [
    "abs", "arccos", "arccosh", "arcsin", "arcsinh", "arctan", "arctanh",
    "cbrt", "ceil", "cos", "cosh", "degrees", "erf", "erfinv", "exp",
    "expm1", "fix", "floor", "gamma", "gammaln", "log", "log10", "log1p",
    "log2", "logical_not", "negative", "radians", "rcbrt", "reciprocal",
    "relu", "rint", "round", "rsqrt", "sigmoid", "sign", "sin", "sinh",
    "softsign", "sqrt", "square", "tan", "tanh", "trunc",
]
ROWS["Elementwise unary (REF:src/operator/tensor/elemwise_unary_op*)"] = [
    (n, "yes", f"nd.{n}", "") for n in _UNARY
] + [
    ("erfcinv", "yes", "nd.erfcinv", ""),
    ("digamma", "yes", "nd.digamma", ""),
    ("hard_sigmoid", "yes", "nd.hard_sigmoid", ""),
    ("softrelu", "yes", "nd.softrelu", "also Activation act_type"),
    ("gelu", "yes", "nd.gelu", "upstream via LeakyReLU act_type='gelu'; first-class here"),
    ("smooth_l1", "yes", "nd.smooth_l1", ""),
    ("make_loss", "yes", "nd.make_loss", ""),
    ("shuffle", "yes", "nd.shuffle", ""),
]

_BCAST = [
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_mod", "broadcast_power", "broadcast_maximum",
    "broadcast_minimum", "broadcast_hypot", "broadcast_equal",
    "broadcast_not_equal", "broadcast_greater", "broadcast_greater_equal",
    "broadcast_lesser", "broadcast_lesser_equal", "broadcast_logical_and",
    "broadcast_logical_or", "broadcast_logical_xor",
]
ROWS["Binary / broadcast (REF:src/operator/tensor/elemwise_binary*_op*, broadcast_reduce_op*)"] = [
    (n, "yes", f"nd.{n}", "") for n in _BCAST
] + [
    ("broadcast_plus", "yes", "nd.broadcast_plus", "alias"),
    ("broadcast_minus", "yes", "nd.broadcast_minus", "alias"),
    ("broadcast_like", "yes", "nd.broadcast_like", ""),
    ("broadcast_to", "yes", "nd.broadcast_to", ""),
    ("broadcast_axis", "yes", "nd.broadcast_axis", ""),
    ("broadcast_axes", "yes", "nd.broadcast_axes", "alias"),
    ("elemwise_add", "yes", "nd.elemwise_add", ""),
    ("elemwise_sub", "yes", "nd.elemwise_sub", ""),
    ("elemwise_mul", "yes", "nd.elemwise_mul", ""),
    ("elemwise_div", "yes", "nd.elemwise_div", ""),
    ("add_n", "yes", "nd.add_n", ""),
    ("maximum", "yes", "nd.maximum", ""),
    ("minimum", "yes", "nd.minimum", ""),
    ("hypot", "yes", "nd.hypot", ""),
    ("equal", "yes", "nd.equal", ""),
    ("not_equal", "yes", "nd.not_equal", ""),
    ("greater", "yes", "nd.greater", ""),
    ("greater_equal", "yes", "nd.greater_equal", ""),
    ("lesser", "yes", "nd.lesser", ""),
    ("lesser_equal", "yes", "nd.lesser_equal", ""),
    ("logical_and", "yes", "nd.logical_and", ""),
    ("logical_or", "yes", "nd.logical_or", ""),
    ("logical_xor", "yes", "nd.logical_xor", ""),
    ("arctan2", "yes", "nd.arctan2", ""),
    ("nextafter", "yes", "nd.nextafter", ""),
]

ROWS["Reductions / ordering / indexing (REF:src/operator/tensor/{broadcast_reduce_op_value,ordering_op,indexing_op}*)"] = [
    ("sum", "yes", "nd.sum", ""),
    ("sum_axis", "yes", "nd.sum_axis", "alias"),
    ("mean", "yes", "nd.mean", ""),
    ("prod", "yes", "nd.prod", ""),
    ("nansum", "yes", "nd.nansum", ""),
    ("nanprod", "yes", "nd.nanprod", ""),
    ("max", "yes", "nd.max", ""),
    ("max_axis", "yes", "nd.max_axis", "alias"),
    ("min", "yes", "nd.min", ""),
    ("min_axis", "yes", "nd.min_axis", "alias"),
    ("norm", "yes", "nd.norm", "ord 1/2, axis"),
    ("argmax", "yes", "nd.argmax", ""),
    ("argmin", "yes", "nd.argmin", ""),
    ("argmax_channel", "yes", "nd.argmax_channel", ""),
    ("pick", "yes", "nd.pick", ""),
    ("topk", "yes", "nd.topk", "ret_typ value/indices/mask/both"),
    ("sort", "yes", "nd.sort", ""),
    ("argsort", "yes", "nd.argsort", ""),
    ("take", "yes", "nd.take", "clip/wrap modes"),
    ("batch_take", "yes", "nd.batch_take", ""),
    ("one_hot", "yes", "nd.one_hot", ""),
    ("gather_nd", "yes", "nd.gather_nd", ""),
    ("scatter_nd", "yes", "nd.scatter_nd", ""),
    ("ravel_multi_index", "yes", "nd.ravel_multi_index", ""),
    ("unravel_index", "yes", "nd.unravel_index", ""),
    ("choose_element_0index", "yes", "nd.choose_element_0index", ""),
    ("fill_element_0index", "yes", "nd.fill_element_0index", ""),
    ("where", "yes", "nd.where", ""),
]

ROWS["Shape / layout / casting (REF:src/operator/tensor/matrix_op*)"] = [
    ("Reshape", "yes", "nd.Reshape", "incl. 0/-1/-2/-3/-4 special codes"),
    ("reshape_like", "yes", "nd.reshape_like", ""),
    ("Flatten", "yes", "nd.Flatten", ""),
    ("expand_dims", "yes", "nd.expand_dims", ""),
    ("squeeze", "yes", "nd.squeeze", ""),
    ("Concat", "yes", "nd.Concat", ""),
    ("stack", "yes", "nd.stack", ""),
    ("split", "yes", "nd.split", ""),
    ("slice", "yes", "nd.slice", "begin/end/step"),
    ("slice_axis", "yes", "nd.slice_axis", ""),
    ("slice_like", "yes", "nd.slice_like", ""),
    ("clip", "yes", "nd.clip", ""),
    ("repeat", "yes", "nd.repeat", ""),
    ("tile", "yes", "nd.tile", ""),
    ("pad", "yes", "nd.pad", ""),
    ("transpose", "yes", "nd.transpose", ""),
    ("swapaxes", "yes", "nd.swapaxes", ""),
    ("flip", "yes", "nd.flip", ""),
    ("reverse", "yes", "nd.reverse", ""),
    ("depth_to_space", "yes", "nd.depth_to_space", ""),
    ("space_to_depth", "yes", "nd.space_to_depth", "also the s2d ResNet stem"),
    ("diag", "yes", "nd.diag", ""),
    ("shape_array", "yes", "nd.shape_array", ""),
    ("size_array", "yes", "nd.size_array", ""),
    ("Cast", "yes", "nd.Cast", ""),
    ("amp_cast", "yes", "nd.amp_cast", ""),
    ("amp_multicast", "yes", "nd.amp_multicast", ""),
    ("zeros_like", "yes", "nd.zeros_like", ""),
    ("ones_like", "yes", "nd.ones_like", ""),
    ("khatri_rao", "yes", "nd.khatri_rao", ""),
    ("im2col", "yes", "nd.im2col", ""),
    ("col2im", "yes", "nd.col2im", ""),
    ("moments", "yes", "nd.moments", ""),
    ("all_finite", "yes", "nd.all_finite", ""),
    ("multi_all_finite", "yes", "nd.multi_all_finite", ""),
    ("cumsum", "yes", "nd.cumsum", ""),
]

ROWS["Matrix compute (REF:src/operator/tensor/{dot,la_op}*)"] = [
    ("dot", "yes", "nd.dot", "transpose_a/b; sparse via nd.sparse.dot"),
    ("batch_dot", "yes", "nd.batch_dot", ""),
    ("linalg_gemm", "yes", "nd.linalg_gemm", ""),
    ("linalg_gemm2", "yes", "nd.linalg_gemm2", ""),
    ("linalg_potrf", "yes", "nd.linalg_potrf", ""),
    ("linalg_potri", "yes", "nd.linalg_potri", ""),
    ("linalg_trmm", "yes", "nd.linalg_trmm", ""),
    ("linalg_trsm", "yes", "nd.linalg_trsm", ""),
    ("linalg_sumlogdiag", "yes", "nd.linalg_sumlogdiag", ""),
    ("linalg_syrk", "yes", "nd.linalg_syrk", ""),
    ("linalg_gelqf", "yes", "nd.linalg_gelqf", ""),
    ("linalg_syevd", "yes", "nd.linalg_syevd", ""),
    ("linalg_inverse", "yes", "nd.linalg_inverse", ""),
    ("linalg_det", "yes", "nd.linalg_det", ""),
    ("linalg_slogdet", "yes", "nd.linalg_slogdet", ""),
    ("linalg_extractdiag", "yes", "nd.linalg_extractdiag", ""),
    ("linalg_makediag", "yes", "nd.linalg_makediag", ""),
    ("linalg_extracttrian", "yes", "nd.linalg_extracttrian", ""),
    ("linalg_maketrian", "yes", "nd.linalg_maketrian", ""),
]

ROWS["Random / sampling (REF:src/operator/random/)"] = [
    ("random_uniform", "yes", "nd.random_uniform", ""),
    ("random_normal", "yes", "nd.random_normal", ""),
    ("random_gamma", "yes", "nd.random_gamma", ""),
    ("random_exponential", "yes", "nd.random_exponential", ""),
    ("random_poisson", "yes", "nd.random_poisson", ""),
    ("random_negative_binomial", "yes", "nd.random_negative_binomial", ""),
    ("random_generalized_negative_binomial", "yes",
     "nd.random_generalized_negative_binomial", ""),
    ("random_randint", "yes", "nd.random_randint", ""),
    ("sample_uniform", "yes", "nd.sample_uniform", "per-row distribution params"),
    ("sample_normal", "yes", "nd.sample_normal", ""),
    ("sample_gamma", "yes", "nd.sample_gamma", ""),
    ("sample_exponential", "yes", "nd.sample_exponential", ""),
    ("sample_poisson", "yes", "nd.sample_poisson", ""),
    ("sample_negative_binomial", "yes", "nd.sample_negative_binomial", ""),
    ("sample_generalized_negative_binomial", "yes",
     "nd.sample_generalized_negative_binomial", ""),
    ("sample_multinomial", "yes", "nd.sample_multinomial", ""),
    ("randn", "yes", "nd.randn", ""),
    ("normal", "yes", "nd.normal", "alias"),
    ("uniform", "yes", "nd.uniform", "alias"),
]

ROWS["Optimizer update kernels (REF:src/operator/optimizer_op.cc, contrib/adamw.cc)"] = [
    ("sgd_update", "yes", "nd.sgd_update", ""),
    ("sgd_mom_update", "yes", "nd.sgd_mom_update", "state rebound in place"),
    ("mp_sgd_update", "yes", "nd.mp_sgd_update", "f32 master weights"),
    ("mp_sgd_mom_update", "yes", "nd.mp_sgd_mom_update", ""),
    ("adam_update", "yes", "nd.adam_update", "upstream contract: no bias correction in the kernel"),
    ("nag_mom_update", "yes", "nd.nag_mom_update", ""),
    ("mp_nag_mom_update", "yes", "nd.mp_nag_mom_update", ""),
    ("rmsprop_update", "yes", "nd.rmsprop_update", ""),
    ("rmspropalex_update", "yes", "nd.rmspropalex_update", "centered"),
    ("ftrl_update", "yes", "nd.ftrl_update", ""),
    ("ftml_update", "yes", "nd.ftml_update", ""),
    ("signsgd_update", "yes", "nd.signsgd_update", ""),
    ("signum_update", "yes", "nd.signum_update", ""),
    ("lamb_update_phase1", "yes", "nd.lamb_update_phase1", ""),
    ("lamb_update_phase2", "yes", "nd.lamb_update_phase2", ""),
    ("adamw_update", "yes", "nd.adamw_update", "tensor rescale_grad accepted"),
    ("mp_adamw_update", "yes", "nd.mp_adamw_update", ""),
    ("multi_sgd_update", "yes", "nd.multi_sgd_update",
     "interleaved varargs; all updates traced into ONE XLA program (the fusion the reference's kernel gave); Trainer.step_all is the class-level fused path"),
    ("multi_sgd_mom_update", "yes", "nd.multi_sgd_mom_update", ""),
    ("multi_mp_sgd_update", "yes", "nd.multi_mp_sgd_update", ""),
    ("multi_mp_sgd_mom_update", "yes", "nd.multi_mp_sgd_mom_update", ""),
    ("preloaded_multi_sgd_update", "yes", "nd.preloaded_multi_sgd_update",
     "lrs/wds as device tensors"),
    ("preloaded_multi_sgd_mom_update", "yes",
     "nd.preloaded_multi_sgd_mom_update", ""),
    ("preloaded_multi_mp_sgd_update", "yes",
     "nd.preloaded_multi_mp_sgd_update", ""),
    ("preloaded_multi_mp_sgd_mom_update", "yes",
     "nd.preloaded_multi_mp_sgd_mom_update", ""),
    ("multi_lars", "divergent", "optimizer.LBSGD", "LARS trust ratios computed per-layer inside LBSGD.update_core"),
    ("lars_multi_sgd_update", "divergent", "optimizer.LBSGD", "same (4 variants)"),
]

ROWS["Contrib — detection / vision (REF:src/operator/contrib/)"] = [
    ("MultiBoxPrior", "yes", "nd.contrib.MultiBoxPrior", ""),
    ("MultiBoxTarget", "yes", "nd.contrib.MultiBoxTarget", ""),
    ("MultiBoxDetection", "yes", "nd.contrib.MultiBoxDetection", ""),
    ("box_nms", "yes", "nd.contrib.box_nms", "fixed-capacity padded TPU formulation"),
    ("box_iou", "yes", "nd.contrib.box_iou", ""),
    ("bipartite_matching", "yes", "nd.contrib.bipartite_matching", ""),
    ("Proposal", "yes", "nd.Proposal", ""),
    ("MultiProposal", "yes", "nd.MultiProposal", ""),
    ("ROIAlign", "yes", "nd.ROIAlign", ""),
    ("DeformableConvolution", "yes", "nd.contrib.DeformableConvolution",
     "bilinear-gather formulation"),
    ("DeformablePSROIPooling", "yes", "nd.DeformablePSROIPooling",
     "bilinear-sampled, learned per-bin offsets; edge-clamp divergence noted in docstring"),
    ("PSROIPooling", "yes", "nd.PSROIPooling",
     "position-sensitive channel mapping; bins averaged over a fixed 4x4 sample grid (subsamples the reference's full quantized-cell average for bins wider than ~4 cells — documented in the docstring); ROIAlign(position_sensitive=True) is the aligned variant"),
    ("BilinearResize2D", "yes", "nd.BilinearResize2D", ""),
    ("AdaptiveAvgPooling2D", "yes", "nd.contrib.AdaptiveAvgPooling2D",
     "averaging-matrix einsum formulation (MXU-friendly)"),
]

ROWS["Contrib — misc (REF:src/operator/contrib/)"] = [
    ("count_sketch", "yes", "nd.contrib.count_sketch", ""),
    ("fft", "yes", "nd.contrib.fft", "XLA fft; interleaved re/im layout preserved"),
    ("ifft", "yes", "nd.contrib.ifft", "unnormalized like cuFFT"),
    ("quadratic", "yes", "nd.contrib.quadratic", ""),
    ("allclose", "yes", "nd.contrib.allclose", ""),
    ("arange_like", "yes", "nd.contrib.arange_like", ""),
    ("div_sqrt_dim", "yes", "nd.contrib.div_sqrt_dim", ""),
    ("index_copy", "yes", "nd.contrib.index_copy", ""),
    ("index_array", "yes", "nd.contrib.index_array", ""),
    ("boolean_mask", "yes", "nd.contrib.boolean_mask", ""),
    ("gradientmultiplier", "yes", "nd.contrib.gradientmultiplier", ""),
    ("cond", "yes", "nd.contrib.cond", "lax.cond when traced"),
    ("foreach", "yes", "nd.contrib.foreach", "lax.scan when traced"),
    ("while_loop", "yes", "nd.contrib.while_loop", "lax.while_loop when traced"),
    ("interleaved_matmul_selfatt_qk", "divergent", "kernels.flash_attention",
     "the 1.6 interleaved attention matmuls are subsumed by the fused flash-attention Pallas kernel (better than the reference's unfused pair)"),
    ("interleaved_matmul_selfatt_valatt", "divergent", "kernels.flash_attention", "same"),
    ("interleaved_matmul_encdec_qk", "divergent", "kernels.flash_attention", "same"),
    ("interleaved_matmul_encdec_valatt", "divergent", "kernels.flash_attention", "same"),
    ("hawkesll", "yes", "nd.contrib.hawkesll",
     "lax.scan O(1)-per-event excitation recursion; brute-force-oracle and state-carry composition tested"),
    ("dgl_csr_neighbor_uniform_sample", "not-planned", "",
     "DGL graph-sampling family (6 ops): graph workloads out of scope per SURVEY"),
    ("edge_id", "not-planned", "", "DGL family"),
    ("getnnz", "divergent", "nd.sparse",
     "CSR indptr[-1] IS the nnz; no separate kernel needed"),
    ("quantize", "yes", "nd.quantize_v2", "v2 entry is the documented one"),
    ("quantize_v2", "yes", "nd.quantize_v2", ""),
    ("dequantize", "yes", "nd.dequantize", ""),
    ("requantize", "yes", "nd.requantize", ""),
    ("quantized_conv", "yes", "nd.quantized_conv", "int8 lax.conv"),
    ("quantized_fully_connected", "yes", "nd.quantized_fully_connected", ""),
    ("quantized_flatten", "yes", "nd.quantized_flatten", ""),
    ("quantized_pooling", "yes", "nd.quantized_pooling", "int8 passthrough pooling"),
    ("amp_cast (contrib→core in 1.5)", "yes", "nd.amp_cast", ""),
]

ROWS["Sparse (REF:src/operator/tensor/{cast_storage,dot,elemwise*}-inl.h sparse paths)"] = [
    ("cast_storage", "yes", "nd.sparse.cast_storage", "divergence #5: compact gather/segment-sum formulation"),
    ("sparse dot (csr)", "yes", "nd.sparse.dot", ""),
    ("sparse elemwise_add", "yes", "nd.sparse.elemwise_add", ""),
    ("retain", "yes", "nd.sparse.retain", ""),
    ("row_sparse_array", "yes", "nd.sparse.row_sparse_array", ""),
    ("csr_matrix", "yes", "nd.sparse.csr_matrix", ""),
]

ROWS["Internal registrations (blanket rows)"] = [
    ("_backward_* (~300 registrations)", "divergent", "jax.vjp",
     "every backward kernel is derived by JAX autodiff from the forward; no hand-written backward registry exists or is needed"),
    ("_np_* / _npi_* (numpy namespace)", "yes", "tpu_mx.np",
     "211-symbol np namespace mirrors the 1.6+ numpy API"),
    ("_contrib_*AMP loss-scale helpers", "yes", "contrib.amp",
     "LossScaler + cast lists"),
    ("_image_* (image ops)", "yes", "image.image / gluon.data.vision.transforms",
     "resize/crop/flip/normalize etc."),
    ("_sparse_* storage-fallback registrations", "divergent", "nd.sparse",
     "dense-fallback is automatic (jnp); explicit storage types only where they pay"),
]


def counts():
    total = impl = div = np_ = 0
    for fam in ROWS.values():
        for _, status, _, _ in fam:
            total += 1
            impl += status == "yes"
            div += status == "divergent"
            np_ += status == "not-planned"
    return total, impl, div, np_


def render():
    total, impl, div, np_ = counts()
    out = [
        "# OPS_PARITY — upstream MXNet 1.x op registry vs tpu_mx",
        "",
        "Generated by `python tools/ops_parity.py > OPS_PARITY.md` — edit",
        "`tools/ops_parity.py`, not this file.  Checked by",
        "`tests/test_ops_parity.py`: the table must be in sync, every",
        "`yes` row must resolve to a callable, and every smoke template",
        "must execute.",
        "",
        f"**Coverage: {impl} implemented + {div} divergent (documented "
        f"TPU-native replacement) + {np_} not-planned = {total} rows.**",
        "",
        "Statuses: `yes` = implemented (smoke-invoked in CI); `divergent`",
        "= capability delivered through a documented TPU-native design",
        "(docs/DIVERGENCES.md); `not-planned` = deliberately absent with",
        "reason.",
        "",
    ]
    for fam, rows in ROWS.items():
        out.append(f"## {fam}")
        out.append("")
        out.append("| op | status | tpu_mx | note |")
        out.append("|---|---|---|---|")
        for name, status, impl_, note in rows:
            out.append(f"| `{name}` | {status} | "
                       f"{f'`{impl_}`' if impl_ else '—'} | {note} |")
        out.append("")
    return "\n".join(out)


if __name__ == "__main__":
    print(render())
