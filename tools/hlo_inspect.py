"""HLO inspection for compiled train steps (VERDICT r2 ask#1: "nobody has
looked at the steady-state HLO yet").

Builds the bench workload's CompiledTrainStep, lowers+compiles it for the
current backend, and prints an op histogram with the layout-change smells
called out: `transpose`, `copy`, `pad`, `reshape`, `convert` counts, the
fusion count, and every convolution's shapes/layout line.  Run on the real
TPU (plain `python tools/hlo_inspect.py resnet`) to see what XLA actually
made of the step; `--smoke` uses tiny shapes for a CPU sanity pass.

Usage: python tools/hlo_inspect.py {resnet|bert|lstm|ssd} [--smoke] [--batch N]
"""
import argparse
import collections
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_resnet_step(smoke, batch, layout="NHWC", stem="s2d"):
    import numpy as np
    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.gluon.model_zoo import vision
    from tpu_mx.layout import default_layout
    from tpu_mx.parallel import CompiledTrainStep

    size = 64 if smoke else 224
    classes = 100 if smoke else 1000
    factory = "resnet18_v1" if smoke else "resnet50_v1"
    shape = (batch, size, size, 3) if layout == "NHWC" else (batch, 3, size,
                                                             size)
    with default_layout(layout):
        net = getattr(vision, factory)(classes=classes, stem=stem)
    net.initialize(init="xavier")
    # tiny on-device finalize + on-device data, mirroring bench.py's
    # tunnel-lean cold start (chip_profile runs this builder ON CHIP)
    net.finalize_shapes(nd.random.uniform(shape=(2,) + shape[1:]))
    net.cast("bfloat16")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9,
                              wd=1e-4, multi_precision=True)
    step = CompiledTrainStep(net, loss_fn, opt, mesh=None)
    data = nd.cast(nd.random.uniform(shape=shape), "bfloat16")
    label = nd.random.randint(0, classes, (batch,), dtype="float32")
    return step, (data, label)


def build_bert_step(smoke, batch):
    import numpy as np
    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.models.bert import BERTModel, bert_base_config
    from tpu_mx.parallel import CompiledTrainStep

    seq_len = 128
    cfg = bert_base_config(vocab_size=1000 if smoke else 30522,
                           max_len=seq_len)
    if smoke:
        cfg.update(num_layers=2, units=128, hidden_size=512, num_heads=2)
    net = BERTModel(cfg, dtype="bfloat16", remat=not smoke,
                    remat_policy=os.environ.get("BENCH_BERT_REMAT_POLICY")
                    or None)
    net.initialize()
    rng = np.random.RandomState(0)
    tokens = rng.randint(4, cfg["vocab_size"], (batch, seq_len)).astype(
        np.int32)
    types = np.zeros((batch, seq_len), np.int32)
    n_masked = max(1, int(0.15 * seq_len))
    positions = np.stack([rng.choice(seq_len, n_masked, replace=False)
                          for _ in range(batch)]).astype(np.int32)
    labels = np.take_along_axis(tokens, positions, axis=1)
    net.finalize_shapes(nd.array(tokens[:1]), nd.array(types[:1]), None,
                        nd.array(positions[:1]))
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    class MLMLoss(gluon.loss.Loss):
        def __init__(self):
            super().__init__(weight=None, batch_axis=0)

        def hybrid_forward(self, F, logits, labels):
            vocab = logits.shape[-1]
            return F.mean(ce(F.reshape(logits, shape=(-1, vocab)),
                             F.reshape(labels, shape=(-1,))))

    opt = mx.optimizer.create("lamb", learning_rate=1e-4,
                              multi_precision=True)
    step = CompiledTrainStep(net, MLMLoss(), opt)
    return step, (nd.array(tokens), nd.array(types), None,
                  nd.array(positions), nd.array(labels))


def build_lstm_step(smoke, batch):
    """The bench's PTB LSTM leg (bf16 weights, f32 CE logits) — mirrors
    bench.py _lstm_once so dtype_audit sees the hardware configuration.
    KEEP IN SYNC with bench.py: a bench-side change (loss/optimizer/
    dtype knob) silently desynchronizes the audited program from the
    benched one."""
    import numpy as np
    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.models.lstm_lm import RNNModel
    from tpu_mx.parallel import CompiledTrainStep

    vocab, emb, hid, layers, bptt = (1000, 64, 64, 1, 8) if smoke else \
        (10000, 650, 650, 2, 35)
    model = RNNModel(mode="lstm", vocab_size=vocab, num_embed=emb,
                     num_hidden=hid, num_layers=layers, dropout=0.0)
    model.initialize(init="xavier")

    class FlatCE(gluon.loss.Loss):
        def __init__(self, **kw):
            super().__init__(weight=None, batch_axis=0, **kw)
            self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

        def hybrid_forward(self, F, logits, labels):
            v = logits.shape[-1]
            return self._ce(
                F.cast(F.reshape(logits, shape=(-1, v)), dtype="float32"),
                F.reshape(labels, shape=(-1,)))

    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, vocab, (bptt, batch)), dtype="float32")
    y = nd.array(rng.randint(0, vocab, (bptt * batch,)), dtype="float32")
    model.finalize_shapes(x)  # no-op: RNNModel declares every dim
    model.cast("bfloat16")
    opt = mx.optimizer.create("sgd", learning_rate=1.0,
                              multi_precision=True)
    step = CompiledTrainStep(model, FlatCE(), opt)
    return step, (x, y)


def build_ssd_step(smoke, batch):
    """The bench's SSD leg (bf16 backbone, f32 heads/targets/losses) —
    mirrors bench.py _ssd_once (vgg16_reduced official config).
    KEEP IN SYNC with bench.py (see build_lstm_step note)."""
    import numpy as np
    import tpu_mx as mx
    from tpu_mx import autograd, gluon, nd
    from tpu_mx.gluon.block import HybridBlock
    from tpu_mx.models.ssd import SSD, SSDTrainingTargets, ssd_512
    from tpu_mx.parallel import CompiledTrainStep

    if smoke:
        size, classes = 64, 3
        net = SSD(classes, sizes=[[0.2, 0.35], [0.5, 0.7]],
                  ratios=[[1, 2, 0.5]] * 2, base_filters=(8, 16))
    else:
        size, classes = 512, 20
        net = ssd_512(classes, backbone="vgg16_reduced")
    targets = SSDTrainingTargets()

    class SSDTrain(HybridBlock):
        def __init__(self, ssd_net, **kw):
            super().__init__(**kw)
            self.net = ssd_net
            self._cls = gluon.loss.SoftmaxCrossEntropyLoss()
            self._box = gluon.loss.HuberLoss()

        def forward(self, x, labels):
            anchors, cls_preds, box_preds = self.net(x)
            anchors = nd.cast(anchors, "float32")
            cls_preds = nd.cast(cls_preds, "float32")
            box_preds = nd.cast(box_preds, "float32")
            with autograd.pause():
                loc_t, loc_m, cls_t = targets(anchors, labels, cls_preds)
            return self._cls(cls_preds, cls_t) + \
                self._box(box_preds * loc_m, loc_t * loc_m)

    wrapper = SSDTrain(net)
    wrapper.initialize(init="xavier")
    rng = np.random.RandomState(0)
    labels = np.full((batch, 2, 5), -1.0, np.float32)
    for b in range(batch):
        cls = rng.randint(0, classes)
        x0, y0 = rng.uniform(0.05, 0.5, 2)
        labels[b, 0] = [cls, x0, y0, min(x0 + 0.3, 0.95),
                        min(y0 + 0.3, 0.95)]
    x_nd = nd.random.uniform(high=0.1, shape=(batch, 3, size, size))
    l_nd = nd.array(labels)
    wrapper.finalize_shapes(x_nd[:2], l_nd[:2])
    wrapper.cast("bfloat16")
    x_nd = nd.cast(x_nd, "bfloat16")
    dummy = nd.array(np.zeros((1,), np.float32))
    opt = mx.optimizer.create("sgd", learning_rate=0.01, momentum=0.9,
                              wd=5e-4, multi_precision=True)
    step = CompiledTrainStep(wrapper, gluon.loss.PassThrough(), opt)
    return step, (x_nd, l_nd, dummy)


SMELLS = ("transpose", "copy", "pad", "reshape", "convert", "bitcast",
          "all-reduce", "dynamic-slice", "dynamic-update-slice", "gather",
          "scatter")


def analyze(hlo_text):
    ops = collections.Counter()
    convs = []
    fusions = 0
    for line in hlo_text.splitlines():
        m = re.match(r"\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*[^ ]+\s+([\w\-]+)\(",
                     line)
        if not m:
            continue
        op = m.group(1)
        ops[op] += 1
        if op == "fusion":
            fusions += 1
        if op == "convolution":
            convs.append(line.strip()[:180])
    return ops, convs, fusions


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("model", choices=["resnet", "bert", "lstm", "ssd"])
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--dump", help="write full HLO text here")
    args = ap.parse_args()

    batch = args.batch or (8 if args.smoke else 256)
    builders = {"resnet": build_resnet_step, "bert": build_bert_step,
                "lstm": build_lstm_step, "ssd": build_ssd_step}
    step, batch_args = builders[args.model](args.smoke, batch)

    # trigger the build without running a step, then compile the jitted fn
    raw = tuple(b._data if b is not None and hasattr(b, "_data") else b
                for b in batch_args)
    if step._jitted is None:
        step._build(len(raw))
        step.place()
    import jax
    import jax.numpy as jnp
    from tpu_mx import random as _random
    key = _random.take_key()
    gacc = step._gacc if step._accum > 1 else {}
    compiled = step._jitted.lower(
        step.values, step.masters, step.opt_states, step._efs, gacc,
        jnp.asarray(1.0, jnp.float32), jnp.asarray(0.1, jnp.float32),
        key, *raw).compile()
    txt = compiled.as_text()
    if args.dump:
        with open(args.dump, "w") as f:
            f.write(txt)
    ops, convs, fusions = analyze(txt)
    print(f"== {args.model} train-step HLO ({len(txt.splitlines())} lines, "
          f"{fusions} fusions) ==")
    print("-- op histogram (top 25) --")
    for op, n in ops.most_common(25):
        mark = "  <-- layout/copy smell" if op in SMELLS else ""
        print(f"  {op:28s} {n}{mark}")
    print("-- convolutions --")
    for c in convs:
        print("  " + c)
    try:
        mem = compiled.memory_analysis()
        print(f"-- memory: {mem}")
    except Exception:
        pass
    cost = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = cost.get("flops") if hasattr(cost, "get") else None
        if flops:
            print(f"-- cost_analysis flops/step: {flops:.3e}")
    except Exception:
        pass


if __name__ == "__main__":
    main()
