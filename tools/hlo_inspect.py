"""HLO inspection for compiled train steps (VERDICT r2 ask#1: "nobody has
looked at the steady-state HLO yet").

Builds the bench workload's CompiledTrainStep, lowers+compiles it for the
current backend, and prints an op histogram with the layout-change smells
called out: `transpose`, `copy`, `pad`, `reshape`, `convert` counts, the
fusion count, and every convolution's shapes/layout line.  Run on the real
TPU (plain `python tools/hlo_inspect.py resnet`) to see what XLA actually
made of the step; `--smoke` uses tiny shapes for a CPU sanity pass.

Usage: python tools/hlo_inspect.py {resnet|bert} [--smoke] [--batch N]
"""
import argparse
import collections
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_resnet_step(smoke, batch, layout="NHWC", stem="s2d"):
    import numpy as np
    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.gluon.model_zoo import vision
    from tpu_mx.layout import default_layout
    from tpu_mx.parallel import CompiledTrainStep

    size = 64 if smoke else 224
    classes = 100 if smoke else 1000
    factory = "resnet18_v1" if smoke else "resnet50_v1"
    shape = (batch, size, size, 3) if layout == "NHWC" else (batch, 3, size,
                                                             size)
    with default_layout(layout):
        net = getattr(vision, factory)(classes=classes, stem=stem)
    net.initialize(init="xavier")
    x = nd.array(np.random.rand(*shape).astype(np.float32))
    net(x)
    net.cast("bfloat16")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9,
                              wd=1e-4, multi_precision=True)
    step = CompiledTrainStep(net, loss_fn, opt, mesh=None)
    data = nd.cast(nd.array(np.random.rand(*shape).astype(np.float32)),
                   "bfloat16")
    label = nd.array(np.random.randint(0, classes, (batch,)), dtype="float32")
    return step, (data, label)


def build_bert_step(smoke, batch):
    import numpy as np
    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.models.bert import BERTModel, bert_base_config
    from tpu_mx.parallel import CompiledTrainStep

    seq_len = 128
    cfg = bert_base_config(vocab_size=1000 if smoke else 30522,
                           max_len=seq_len)
    if smoke:
        cfg.update(num_layers=2, units=128, hidden_size=512, num_heads=2)
    net = BERTModel(cfg, dtype="bfloat16", remat=not smoke,
                    remat_policy=os.environ.get("BENCH_BERT_REMAT_POLICY")
                    or None)
    net.initialize()
    rng = np.random.RandomState(0)
    tokens = rng.randint(4, cfg["vocab_size"], (batch, seq_len)).astype(
        np.int32)
    types = np.zeros((batch, seq_len), np.int32)
    n_masked = max(1, int(0.15 * seq_len))
    positions = np.stack([rng.choice(seq_len, n_masked, replace=False)
                          for _ in range(batch)]).astype(np.int32)
    labels = np.take_along_axis(tokens, positions, axis=1)
    net(nd.array(tokens[:1]), nd.array(types[:1]), None,
        nd.array(positions[:1]))
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    class MLMLoss(gluon.loss.Loss):
        def __init__(self):
            super().__init__(weight=None, batch_axis=0)

        def hybrid_forward(self, F, logits, labels):
            vocab = logits.shape[-1]
            return F.mean(ce(F.reshape(logits, shape=(-1, vocab)),
                             F.reshape(labels, shape=(-1,))))

    opt = mx.optimizer.create("lamb", learning_rate=1e-4,
                              multi_precision=True)
    step = CompiledTrainStep(net, MLMLoss(), opt)
    return step, (nd.array(tokens), nd.array(types), None,
                  nd.array(positions), nd.array(labels))


SMELLS = ("transpose", "copy", "pad", "reshape", "convert", "bitcast",
          "all-reduce", "dynamic-slice", "dynamic-update-slice", "gather",
          "scatter")


def analyze(hlo_text):
    ops = collections.Counter()
    convs = []
    fusions = 0
    for line in hlo_text.splitlines():
        m = re.match(r"\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*[^ ]+\s+([\w\-]+)\(",
                     line)
        if not m:
            continue
        op = m.group(1)
        ops[op] += 1
        if op == "fusion":
            fusions += 1
        if op == "convolution":
            convs.append(line.strip()[:180])
    return ops, convs, fusions


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("model", choices=["resnet", "bert"])
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--dump", help="write full HLO text here")
    args = ap.parse_args()

    batch = args.batch or (8 if args.smoke else 256)
    if args.model == "resnet":
        step, batch_args = build_resnet_step(args.smoke, batch)
    else:
        step, batch_args = build_bert_step(args.smoke, batch)

    # trigger the build without running a step, then compile the jitted fn
    raw = tuple(b._data if b is not None and hasattr(b, "_data") else b
                for b in batch_args)
    if step._jitted is None:
        step._build(len(raw))
        step.place()
    import jax
    import jax.numpy as jnp
    from tpu_mx import random as _random
    key = _random.take_key()
    gacc = step._gacc if step._accum > 1 else {}
    compiled = step._jitted.lower(
        step.values, step.masters, step.opt_states, step._efs, gacc,
        jnp.asarray(1.0, jnp.float32), jnp.asarray(0.1, jnp.float32),
        key, *raw).compile()
    txt = compiled.as_text()
    if args.dump:
        with open(args.dump, "w") as f:
            f.write(txt)
    ops, convs, fusions = analyze(txt)
    print(f"== {args.model} train-step HLO ({len(txt.splitlines())} lines, "
          f"{fusions} fusions) ==")
    print("-- op histogram (top 25) --")
    for op, n in ops.most_common(25):
        mark = "  <-- layout/copy smell" if op in SMELLS else ""
        print(f"  {op:28s} {n}{mark}")
    print("-- convolutions --")
    for c in convs:
        print("  " + c)
    try:
        mem = compiled.memory_analysis()
        print(f"-- memory: {mem}")
    except Exception:
        pass
    cost = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = cost.get("flops") if hasattr(cost, "get") else None
        if flops:
            print(f"-- cost_analysis flops/step: {flops:.3e}")
    except Exception:
        pass


if __name__ == "__main__":
    main()
