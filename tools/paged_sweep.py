"""Paged-decode KV block-size sweep + dense/flash crossover disposition.

ISSUE 9's tuning satellite, on the bench harness's decode_attention
micro-arm (bench.measure_decode_micro — the same fixed-seed A/B the
serve leg persists):

- **Block-size sweep**: the serving KV block size trades free-list
  churn (amortized ``1/block_size`` pops per token) against padded-tail
  waste, table length and gather granularity.  Each (block_size,
  context) cell measures the paged arm (device pool, block-table
  program) and the dense-gather arm per decode step.  The default lives
  at ``tpu_mx/kernels/paged_attention.py DEFAULT_BLOCK_SIZE``; update it
  only with receipts from this tool.
- **TPUMX_DENSE_MAX_KV crossover**: the dense/flash dispatch constant
  (ring_attention, default 512, pinned by BENCH_INTERIM_r04 on chip and
  flagged "expected to move" after the r5 native-dtype dot change) is a
  TPU-kernel-vs-XLA-dense crossover: it CANNOT be measured off-TPU
  (interpret-mode Pallas timing is meaningless).  On a TPU backend this
  tool defers to tools/flash_sweep.py — the existing per-(block_q,
  block_k) sweep — and records that pointer; on CPU it records an
  explicit ``skipped`` disposition so a TPU-less round leaves an honest
  artifact instead of silence.

ISSUE 16 widens the sweep with a **Tq axis**: the speculative verify
call batches ``Tq`` query positions per sequence into ONE attention
step, so each (block_size, context, tq) cell now records per-TOKEN
amortization (``*_us_per_tok``).  Row keys carry the axis
(``bs{B}_ctx{C}_tq{T}``) and the record is stamped ``record_rev=2``:
a rev-1 artifact (``bs{B}_ctx{C}`` keys, no tq field) uses a DIFFERENT
keyspace, so this tool REFUSES to merge into one — rename it or start
a new TPUMX_ROUND rather than mixing row schemas.

Artifact-protocol semantics (tools/artifact_protocol.py): rows merge on
rerun, writes are atomic, and a TPU-less run refuses to clobber a
platform=tpu artifact.

    TPUMX_ROUND=r08 python tools/paged_sweep.py \
        [--block-sizes 8,16,32,64] [--contexts 256,1024] \
        [--tq 1,4] [--batch 4]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from artifact_protocol import (artifact, load_prior,  # noqa: E402
                               merge_prior_sections, refuses_clobber,
                               write_atomic)

DEFAULT_BLOCK_SIZES = (8, 16, 32, 64)
DEFAULT_CONTEXTS = (256, 1024)
DEFAULT_TQS = (1, 4)
# rev 2 (ISSUE 16): rows gained the Tq axis — keys are bs{B}_ctx{C}_tq{T}
# and carry a "tq" field.  Bump on any row-keyspace/schema change.
RECORD_REV = 2


def log(msg):
    print(f"[paged_sweep {time.strftime('%H:%M:%S')}] {msg}",
          file=sys.stderr, flush=True)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--block-sizes", default=",".join(
        str(b) for b in DEFAULT_BLOCK_SIZES))
    ap.add_argument("--contexts", default=",".join(
        str(c) for c in DEFAULT_CONTEXTS))
    ap.add_argument("--tq", default=",".join(str(t) for t in DEFAULT_TQS),
                    help="query-window widths (speculative verify Tq)")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--out", default=artifact("PAGED_SWEEP"))
    args = ap.parse_args()
    block_sizes = [int(b) for b in args.block_sizes.split(",") if b]
    contexts = [int(c) for c in args.contexts.split(",") if c]
    tqs = [int(t) for t in args.tq.split(",") if t]
    if any(t < 1 for t in tqs):
        log(f"--tq must be >= 1, got {tqs}")
        return 1

    import jax
    import bench

    platform = jax.default_backend()
    prior = load_prior(args.out)
    if refuses_clobber(prior, platform):
        log(f"{args.out} holds platform=tpu rows; this {platform} run "
            "refuses to clobber them (artifact protocol)")
        return 1
    if prior and prior.get("record_rev", 1) != RECORD_REV:
        # a rev-1 artifact keys rows WITHOUT the tq axis: merging would
        # mix keyspaces and a later reader could double-count.  Refuse.
        log(f"{args.out} is record_rev={prior.get('record_rev', 1)} "
            f"(this tool writes rev {RECORD_REV}, row keys now carry "
            "the tq axis) — rename the old artifact or start a new "
            "TPUMX_ROUND instead of mixing row schemas")
        return 1

    record = {
        "record_rev": RECORD_REV,
        "platform": platform,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "git_head": bench._git_head(),
        "geometry": {"batch": args.batch, "heads": args.heads,
                     "dim": args.dim},
        "rows": {},
    }
    # graft prior rows in BEFORE the first per-row write: the row-at-a-
    # time durability writes below must never clobber sibling rows from
    # an earlier (e.g. partial-retry) run — this run's rows still win
    # their own keys as they land (merge-on-write contract)
    merge_prior_sections(record, prior, ["rows"],
                         require_platform=platform)
    for bs in block_sizes:
        # contexts must tile meaningfully: skip block sizes larger than
        # the shortest context rather than measuring a 1-block table
        usable = [c for c in contexts if c >= bs * 2]
        if not usable:
            log(f"block_size={bs}: no usable context (all < 2 blocks), "
                "skipped")
            continue
        for tq in tqs:
            # every window row needs >= 1 attendable key: ctx > tq
            win = [c for c in usable if c > tq]
            if not win:
                log(f"block_size={bs} tq={tq}: no usable context, "
                    "skipped")
                continue
            log(f"block_size={bs} tq={tq}: contexts {win}")
            rows = bench.measure_decode_micro(win, block_size=bs,
                                              batch=args.batch,
                                              heads=args.heads,
                                              dim=args.dim, tq=tq)
            for row in rows:
                key = f"bs{bs}_ctx{row['context']}_tq{tq}"
                record["rows"][key] = row
                write_atomic(args.out, record)  # row-at-a-time durability

    # honest disposition for the dense/flash crossover constant
    if platform == "tpu":
        record["dense_max_kv_crossover"] = {
            "status": "measure_with_flash_sweep",
            "note": "run tools/flash_sweep.py on this chip; "
                    "TPUMX_DENSE_MAX_KV moves only on its receipts "
                    "(BENCH_INTERIM_r04 pinned 512)",
        }
    else:
        record["dense_max_kv_crossover"] = {
            "status": "skipped",
            "note": f"backend={platform}: the dense/flash crossover is a "
                    "TPU Mosaic-vs-XLA property; interpret-mode timing "
                    "is meaningless.  Constant stands at 512 "
                    "(BENCH_INTERIM_r04 receipts) until a chip round "
                    "reruns tools/flash_sweep.py post-r5-native-dtype.",
        }
    write_atomic(args.out, record)
    if not record["rows"]:
        log(f"done: 0 rows (every block size skipped for the given "
            f"contexts) -> {args.out} holds the disposition only")
        return 0
    best = min(record["rows"].values(),
               key=lambda r: r["paged_us_per_seq"])
    log(f"done: {len(record['rows'])} rows -> {args.out}; best "
        f"paged us/seq: bs{best['block_size']}@ctx{best['context']} = "
        f"{best['paged_us_per_seq']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
