#!/usr/bin/env python
"""tpumx-lint: framework-aware static analysis for the tpu-mx contracts.

PRs 2-5 established hard runtime contracts that, until now, were enforced
only dynamically — by whichever chaos/soak/obs CI schedule happened to
execute the offending branch.  This tool makes them checkable at review
time on EVERY line, including cold error paths no fault schedule reaches.

Since ISSUE 10 the linter is a **two-phase analyzer**: phase 1 builds a
project-wide index — symbol table, call graph, per-function summaries
(locks held at call sites, implicit syncs, raw parameter writes, jit
boundaries) — and phase 2 runs the rule passes against it:

- **durability** — every state write must go through
  ``checkpoint.atomic_write``; with the index, a wrapper around
  ``open(path, "w")`` is caught one helper hop away.
- **determinism** — library RNG must flow through ``tpu_mx/random.py``'s
  process-global state.
- **sync-point** — no implicit device→host syncs inside the hot paths;
  with the index, a helper hiding the ``.item()`` is flagged at the
  call site.
- **concurrency** — thread lifetime + lock discipline; with the index,
  lock context propagates through the call graph, so caller-holds-lock
  helpers are PROVEN safe (no suppression needed) or flagged with a
  lock-free witness chain.
- **telemetry-catalog** — metric/event name literals at emission sites
  must be in ``telemetry.KNOWN_METRICS`` / ``tracing.KNOWN_EVENTS``,
  including sites reached via re-exported aliases across modules.
- **hot-path-purity** — no eager host↔device traffic (``jnp.asarray``
  outside a jit, ``np.asarray`` of device values, ``.item()``,
  per-call ``jax.jit`` construction) reachable from the decode/train/
  fusion hot-path roots through ANY helper chain — the PR-9 decode
  cliff (~73 µs per eager operand) is a lint error now.

Zero third-party dependencies: pure ``ast`` + stdlib, and the metric
catalog is extracted *statically* from ``tpu_mx/telemetry.py`` (the tool
never imports the package, so it runs with no jax in sight).

Suppressions: ``# tpumx-lint: disable=<rule>[,<rule>...] [-- reason]``
on the finding's line, or on a comment-only line directly above it.
Suppress sparingly and always with the ``--`` justification.

Baseline: ``tools/tpumx_lint_baseline.json`` holds fingerprints of
accepted pre-existing findings (``--write-baseline`` regenerates it).
Fingerprints hash (rule, path, enclosing scope, normalized line text) —
stable across unrelated line drift.  The shipped baseline is kept EMPTY:
new findings must be fixed or individually justified inline.

Usage::

    python tools/tpumx_lint.py                  # lint the default tree
    python tools/tpumx_lint.py --format json    # machine-readable (CI)
    python tools/tpumx_lint.py --changed-only   # git-dirty region only
    python tools/tpumx_lint.py --write-baseline # accept current findings
    python tools/tpumx_lint.py path.py ...      # explicit file set

Exit status: 0 when every finding is suppressed or baselined, 1
otherwise, 2 on usage/internal error.  See docs/static_analysis.md for
the rule catalog and how to add a pass.

The implementation lives in the ``tools/lint/`` package (core / index /
passes / cli); this module is the stable entry point and import surface.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from lint import *          # noqa: F401,F403,E402 — the public surface
from lint import cli, core, index, passes  # noqa: F401,E402 — submodules
from lint import (          # noqa: F401,E402 — explicit names for callers
    DEFAULT_INDEX, DEFAULT_TARGETS, HOT_ROOTS, INDEX_FORMAT, LINT_FORMAT,
    REPO, ConcurrencyPass, DeterminismPass, DurabilityPass, FileCtx,
    Finding, HotPathPurityPass, Pass, ProjectIndex, SyncPointPass,
    TelemetryCatalogPass, build_index, build_passes, git_changed_files,
    iter_files, lint_paths, lint_source, lint_sources, load_known_events,
    load_known_metrics, main, read_baseline, read_index, summarize_file,
    suppressed_rules, write_baseline, write_index)

if __name__ == "__main__":
    sys.exit(main())
